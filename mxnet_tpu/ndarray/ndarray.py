"""NDArray: MXNet's mutable tensor, rebuilt as a handle over ``jax.Array``.

Reference: ``python/mxnet/ndarray/ndarray.py`` (class NDArray) over
``include/mxnet/ndarray.h`` + ``src/ndarray/ndarray.cc`` — SURVEY.md §3.1.

TPU-native mapping of the reference's engine semantics (SURVEY.md §2 key
invariant, §4.1):
- async dispatch: jax ops dispatch asynchronously; results are futures.
  ``wait_to_read()`` = ``block_until_ready`` (≙ engine WaitToRead);
  ``asnumpy()`` is the blocking device→host sync point.
- in-place mutation (``a[:]=x``, ``a+=1``): jax arrays are immutable, so the
  handle swaps in a functionally-updated buffer (``.at[].set``). XLA's buffer
  donation recovers the memory; the *semantics* (every alias sees the write)
  are preserved via write-through views.
- views (``Reshape``/``Slice``/``At``): a view NDArray keeps (base, spec
  chain); reads recompose from the base, writes write through to the base —
  emulating the reference's shared-Chunk aliasing.
- async error propagation: XLA raises at the sync point, matching the
  engine's exception-on-var contract (§3.1).
"""
from __future__ import annotations

import functools
import time as _time
import weakref

import numpy as _np

from ..base import MXNetError, numeric_types, integer_types
from ..context import Context, current_context
from .. import autograd as _ag
from ..ops.registry import get_op
from . import dispatch_cache as _dc

__all__ = ["NDArray", "invoke", "array", "waitall", "concatenate"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _jax():
    import jax

    return jax


# live-array tracking for waitall() (reference: Engine::WaitForAll)
_LIVE = weakref.WeakSet()


def waitall():
    """Block until all outstanding computation on live NDArrays finishes.

    Reference: mx.nd.waitall -> Engine::WaitForAll (src/engine/).
    """
    # errors must surface at sync points (engine contract): wait_to_read
    # already wraps async XLA failures as MXNetError — propagate everything
    for arr in list(_LIVE):
        arr.wait_to_read()


class NDArray:
    """n-dimensional array on a Context, with imperative (mutable) semantics.

    Owning arrays hold ``_data`` (a jax.Array). Views hold ``_base`` + a spec
    chain and recompose lazily.
    """

    __slots__ = ("_data", "_base", "_spec", "_ctx", "_version",
                 "_ag_entry", "_grad", "_grad_req",
                 "__weakref__")

    # higher than numpy's so ndarray.__add__(np, NDArray) defers to us
    __array_priority__ = 1000.0

    def __init__(self):
        raise MXNetError("use mx.nd.array / mx.nd.zeros / ... to create NDArrays")

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @classmethod
    def _new(cls):
        self = object.__new__(cls)
        self._data = None
        self._base = None
        self._spec = ()
        self._ctx = None
        self._version = 0
        self._ag_entry = None
        self._grad = None
        self._grad_req = "write"
        _LIVE.add(self)
        return self

    @classmethod
    def _from_jax(cls, value, ctx=None):
        self = cls._new()
        self._data = value
        self._ctx = ctx or current_context()
        return self

    @classmethod
    def _view(cls, base, spec_item):
        root = base._base if base._base is not None else base
        chain = base._spec + (spec_item,)
        self = cls._new()
        self._base = root
        self._spec = chain
        self._ctx = base.context
        return self

    # ------------------------------------------------------------------
    # value access (functional core)
    # ------------------------------------------------------------------
    def _get(self):
        """Current jax value of this handle (recomposing views)."""
        if self._base is None:
            return self._data
        v = self._base._get()
        for kind, arg in self._spec:
            if kind == "index":
                v = v[arg]
            elif kind == "reshape":
                v = v.reshape(arg)
            else:  # pragma: no cover
                raise MXNetError(f"bad view spec {kind}")
        return v

    def _set(self, value):
        """Write a new value through this handle (write-through for views)."""
        if self._base is None:
            if self._data is not None and (tuple(value.shape) != self.shape):
                raise MXNetError(
                    f"cannot assign shape {tuple(value.shape)} to NDArray of "
                    f"shape {self.shape}")
            self._data = value
            self._version += 1
            return
        # recompose: apply the spec chain in reverse against the base
        base = self._base
        jnp = _jnp()

        def apply(v, chain, new):
            if not chain:
                return jnp.asarray(new, dtype=v.dtype)
            (kind, arg), rest = chain[0], chain[1:]
            if kind == "index":
                sub = apply(v[arg], rest, new)
                return v.at[arg].set(sub)
            elif kind == "reshape":
                sub = apply(v.reshape(arg), rest, new)
                return sub.reshape(v.shape)
            raise MXNetError(f"bad view spec {kind}")

        base._set(apply(base._get(), list(self._spec), value))

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._get().shape)

    @property
    def dtype(self):
        return _np.dtype(self._get().dtype)

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        return self._ctx or current_context()

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return self.transpose()

    @property
    def handle(self):  # legacy compat: the jax array IS the handle
        return self._get()

    # ------------------------------------------------------------------
    # sync / host transfer  (reference §4.1: asnumpy == WaitToRead + D2H)
    # ------------------------------------------------------------------
    def wait_to_read(self):
        try:
            v = self._get()
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
        except Exception as e:  # surface async XLA errors as MXNetError
            raise MXNetError(str(e)) from e
        return self

    def asnumpy(self):
        try:
            return _np.asarray(self._get())
        except Exception as e:
            raise MXNetError(str(e)) from e

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer and mark this array as a variable.

        Reference: NDArray.attach_grad -> MXAutogradMarkVariables.
        """
        jnp = _jnp()
        g = NDArray._from_jax(jnp.zeros(self.shape, self.dtype), self.context)
        self._mark_variable(g, grad_req)

    def _mark_variable(self, grad_nd, grad_req="write"):
        self._grad = grad_nd
        self._grad_req = grad_req
        self._ag_entry = _ag.Entry(variable=self, grad_req=grad_req,
                                   shape=self.shape, dtype=self.dtype)

    def zero_grad(self):
        if self._grad is not None:
            jnp = _jnp()
            self._grad._set(jnp.zeros(self._grad.shape, self._grad.dtype))

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad], retain_graph=retain_graph,
                     train_mode=train_mode)

    def detach(self):
        out = NDArray._from_jax(self._get(), self.context)
        return out

    # ------------------------------------------------------------------
    # copies / casts / movement
    # ------------------------------------------------------------------
    def copy(self):
        return NDArray._from_jax(self._get(), self.context)

    def copyto(self, other):
        """Copy into another NDArray (cross-device: ≙ CopyFromTo,
        src/ndarray/ndarray.cc) or to a Context."""
        jax = _jax()
        if isinstance(other, Context):
            v = jax.device_put(self._get(), other.device)
            return NDArray._from_jax(v, other)
        v = jax.device_put(self._get(), other.context.device)
        if tuple(v.shape) != other.shape:
            raise MXNetError("copyto: shape mismatch")
        other._set(v.astype(other.dtype))
        return other

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def astype(self, dtype, copy=True):
        jnp = _jnp()
        v = self._get().astype(_resolve_dtype(dtype))
        return NDArray._from_jax(v, self.context)

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key):
        key = _sanitize_key(key)
        if _ag.is_recording() and _on_tape(self):
            # route through an op so the slice is differentiable (reference
            # records slice ops on the tape too)
            return invoke("_slice_key", [self], {"key": key})
        return NDArray._view(self, ("index", key))

    def __setitem__(self, key, value):
        jnp = _jnp()
        key = _sanitize_key(key)
        if _ag.is_recording() and (
                _on_tape(self) or (isinstance(value, NDArray) and _on_tape(value))):
            # record the sliced write as a differentiable scatter so gradients
            # don't silently vanish (reference hard-part 1: in-place writes
            # are write-var ops on the tape); the handle's tape entry rebinds
            # to the scatter output
            if isinstance(value, NDArray):
                vnd = value
            else:
                vnd = NDArray._from_jax(
                    jnp.asarray(value if isinstance(value, numeric_types)
                                else _np.asarray(value)), self.context)
            out = invoke("_scatter_set_key", [self, vnd], {"key": key})
            self._set(out._get())
            self._ag_entry = out._ag_entry
            return
        if isinstance(value, NDArray):
            v = value._get()
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value))
        cur = self._get()
        self._set(cur.at[key].set(v))

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------
    # operators — all dispatch through the registry so autograd sees them
    # ------------------------------------------------------------------
    def _binary(self, op, other, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return invoke(op, args, {})
        if isinstance(other, numeric_types):
            attrs = {"scalar": float(other), "reverse": reverse}
            return invoke(op + "_scalar", [self], attrs)
        if isinstance(other, (_np.ndarray, list, tuple)):
            o = array(other, ctx=self.context)
            args = [o, self] if reverse else [self, o]
            return invoke(op, args, {})
        return NotImplemented

    def __add__(self, o):
        return self._binary("broadcast_add", o)

    def __radd__(self, o):
        return self._binary("broadcast_add", o, reverse=True)

    def __sub__(self, o):
        return self._binary("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binary("broadcast_sub", o, reverse=True)

    def __mul__(self, o):
        return self._binary("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binary("broadcast_mul", o, reverse=True)

    def __truediv__(self, o):
        return self._binary("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binary("broadcast_div", o, reverse=True)

    def __mod__(self, o):
        return self._binary("broadcast_mod", o)

    def __rmod__(self, o):
        return self._binary("broadcast_mod", o, reverse=True)

    def __pow__(self, o):
        return self._binary("broadcast_power", o)

    def __rpow__(self, o):
        return self._binary("broadcast_power", o, reverse=True)

    def __matmul__(self, o):
        from . import dot as _dot  # storage-dispatching (csr SpMM path)

        return _dot(self, o)

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    # in-place: functional update + handle swap (donation-friendly)
    def __iadd__(self, o):
        r = self._binary("broadcast_add", o)
        self._set(r._get().astype(self._get().dtype))
        return self

    def __isub__(self, o):
        r = self._binary("broadcast_sub", o)
        self._set(r._get().astype(self._get().dtype))
        return self

    def __imul__(self, o):
        r = self._binary("broadcast_mul", o)
        self._set(r._get().astype(self._get().dtype))
        return self

    def __itruediv__(self, o):
        r = self._binary("broadcast_div", o)
        self._set(r._get().astype(self._get().dtype))
        return self

    # comparisons (non-differentiable)
    def __eq__(self, o):
        if o is None:
            return False
        return self._binary("broadcast_equal", o)

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary("broadcast_not_equal", o)

    def __gt__(self, o):
        return self._binary("broadcast_greater", o)

    def __ge__(self, o):
        return self._binary("broadcast_greater_equal", o)

    def __lt__(self, o):
        return self._binary("broadcast_lesser", o)

    def __le__(self, o):
        return self._binary("broadcast_lesser_equal", o)

    __hash__ = object.__hash__  # identity hash (mutable container semantics)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        try:
            s = str(self.asnumpy())
        except MXNetError as e:
            s = f"<error: {e}>"
        return f"\n{s}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # ------------------------------------------------------------------
    # common method surface (delegating to ops)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        new_shape = _infer_reshape(self.shape, tuple(shape))
        if _ag.is_recording() and _on_tape(self):
            return invoke("reshape", [self], {"shape": new_shape})
        return NDArray._view(self, ("reshape", new_shape))

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, axes=None):
        return invoke("transpose", [self], {"axes": axes})

    def flatten(self):
        return invoke("flatten", [self], {})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def swapaxes(self, dim1, dim2):
        return invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": shape})

    def broadcast_like(self, other):
        return invoke("broadcast_to", [self], {"shape": other.shape})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def tile(self, reps):
        return invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def pad(self, *a, **kw):
        return invoke("pad", [self], kw)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("split", [self], {"num_outputs": num_outputs, "axis": axis,
                                        "squeeze_axis": squeeze_axis})

    def clip(self, a_min=None, a_max=None):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return invoke("abs", [self], {})

    def sign(self):
        return invoke("sign", [self], {})

    def sqrt(self):
        return invoke("sqrt", [self], {})

    def square(self):
        return invoke("square", [self], {})

    def exp(self):
        return invoke("exp", [self], {})

    def log(self):
        return invoke("log", [self], {})

    def relu(self):
        return invoke("relu", [self], {})

    def sigmoid(self):
        return invoke("sigmoid", [self], {})

    def tanh(self):
        return invoke("tanh", [self], {})

    def softmax(self, axis=-1):
        return invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", [self], {"axis": axis})

    def sum(self, axis=None, keepdims=False):
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def nansum(self, axis=None, keepdims=False):
        return invoke("nansum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke("topk", [self], {"axis": axis, "k": k, "ret_typ": ret_typ,
                                       "is_ascend": is_ascend})

    def dot(self, other, transpose_a=False, transpose_b=False):
        from . import dot as _dot  # storage-dispatching (csr SpMM path)

        return _dot(self, other, transpose_a=transpose_a,
                    transpose_b=transpose_b)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return invoke("one_hot", [self], {"depth": depth, "on_value": on_value,
                                          "off_value": off_value})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import RowSparseNDArray, CSRNDArray

        if stype == "row_sparse":
            return RowSparseNDArray.from_dense(self._get(), self.context)
        if stype == "csr":
            return CSRNDArray.from_dense(self._get(), self.context)
        raise MXNetError(f"unknown storage type {stype!r}")

    def to_dlpack_for_read(self):
        return self._get().__dlpack__()

    def to_dlpack_for_write(self):
        return self._get().__dlpack__()


# --------------------------------------------------------------------------
# the imperative invoke path (reference: MXImperativeInvokeEx ->
# Imperative::Invoke -> PushFCompute, SURVEY.md §4.1)
# --------------------------------------------------------------------------
def invoke(opname, nd_args, attrs, out=None, ctx=None):
    """Execute a registered op on NDArray inputs.

    1. unwrap inputs (snapshot jax values — free, they're immutable)
    2. run the pure fn (jax dispatches async ≙ Engine::PushAsync) — repeat
       calls go through a jit-cached executable (dispatch_cache.py, the
       CachedOp-style fast path) instead of per-primitive eager dispatch
    3. record on the autograd tape if needed (≙ Imperative::RecordOp)
    4. wrap outputs in NDArrays
    """
    od = get_op(opname)
    if _SYMTRACE["on"]:
        from ..symbol.symbol import SymbolTracer, trace_invoke

        if any(isinstance(a, SymbolTracer) for a in nd_args if a is not None):
            return trace_invoke(opname, nd_args, attrs)
        if _SYMTRACE.get("rng_ops") and od.needs_rng:
            # graph-tier trace (mxnet_tpu.graph.trace): an rng op with no
            # tracer inputs (standalone random creation in forward) must
            # become a graph node drawing from the per-call trace key, not
            # execute eagerly and bake one fixed draw in as a constant
            return trace_invoke(opname, nd_args, attrs)
    nd_args = [a for a in nd_args if a is not None]  # optional inputs omitted
    in_vals = []
    out_ctx = ctx
    for a in nd_args:
        if isinstance(a, NDArray):
            in_vals.append(a._get())
            if out_ctx is None:
                out_ctx = a.context
        else:
            in_vals.append(_jnp().asarray(a))
    if od.needs_rng:
        from .. import random as _rnd
        in_vals = [_rnd._next_key()] + in_vals
        nd_args = [None] + list(nd_args)
    if od.creation and out_ctx is None:
        out_ctx = current_context()

    # jit-cache fast path (dispatch_cache.py): serve a compiled executable
    # keyed on (op, static attrs, input avals, AMP state, ctx kind, train
    # mode).  Keyed on the RAW attrs — filtering is deterministic per raw
    # attrs, so a hit skips it entirely.  Any incompatible mode (unhashable
    # attrs, tracer inputs, trace-scoped RNG, NaiveEngine, blocklisted op)
    # falls through to the plain eager path below.
    fn = None
    call_fn = None
    cache_key = None
    if (_dc.enabled() and od.jit_safe and not _dc.is_blocked(od.name)
            and not _rng_in_trace(od)):
        cache_key = _dc.make_key(
            od.name, attrs, in_vals,
            (_AMP["epoch"] if _AMP["on"] else None),
            (out_ctx.device_type if out_ctx is not None else None),
            _ag.is_training(), stats_name=opname)
        if cache_key is not None:
            # stats keyed on the CALL-SITE name (so aliased ops line up
            # with the profiler's per-op rows); the cache key and the
            # blocklist use the canonical od.name so aliases share entries
            call_fn = _dc.lookup(opname, cache_key)
    if call_fn is None:
        attrs = {k: v for k, v in attrs.items()
                 if v is not None or k in ("axis", "a_min", "a_max")}
        fn = functools.partial(_call_with_attrs, od.fn, attrs)
        if _AMP["on"]:
            # mixed-precision cast policy (contrib.amp): wraps fn so per-op
            # input casts are part of the traced/vjp'd computation —
            # gradients flow back to the original (fp32 master) dtype
            # through the cast's transpose
            fn = _AMP["wrap"](od, fn)
        call_fn = _jax().jit(fn) if cache_key is not None else fn

    recording = (_ag.is_recording() and od.differentiable
                 and any(isinstance(a, NDArray) and _on_tape(a) for a in nd_args if a is not None))

    # per-op timing (reference: engine profiler op events).  Honest timing
    # of an async dispatch requires a sync — same trade the reference's
    # profiler makes via engine bulk-flush.  Snapshot the recorder: another
    # thread's profiler.stop() must not null it mid-op.
    _prof_rec = _PROFILE["record"] if _PROFILE["on"] else None
    if _prof_rec is not None:
        _prof_t0 = _time.perf_counter()

    # fresh compile about to happen (miss path only — hits never get here
    # with fn set): time it for the telemetry compile-event tracer
    _compile_t0 = _time.perf_counter() \
        if (fn is not None and cache_key is not None) else None

    try:
        if recording:
            entries = [(a._ag_entry if isinstance(a, NDArray) else None)
                       for a in nd_args]
            # jit under record_op's vjp: the forward executes compiled and
            # the vjp's transpose compiles too (pjit jvp/transpose rules)
            out_vals, out_entries, multi = _ag.record_op(
                call_fn, in_vals, entries, name=opname)
        else:
            out_vals = call_fn(*in_vals)
            multi = isinstance(out_vals, (tuple, list))
            out_entries = None
    except Exception:
        if fn is None or call_fn is fn:
            raise  # plain eager path (or cached-hit): the error is real
        # first compile of this key failed: retry eagerly.  A real data
        # error raises identically from the eager run and propagates; if
        # eager *succeeds* this (op, attrs, avals) variant is
        # trace-incompatible — cache the EAGER fn in its slot (no retrace
        # on repeats, other variants keep the fast path) and record the
        # failure, escalating to an op-wide block only if more keys fail.
        call_fn = fn
        if recording:
            entries = [(a._ag_entry if isinstance(a, NDArray) else None)
                       for a in nd_args]
            out_vals, out_entries, multi = _ag.record_op(
                fn, in_vals, entries, name=opname)
        else:
            out_vals = fn(*in_vals)
            multi = isinstance(out_vals, (tuple, list))
            out_entries = None
        _dc.mark_unsafe(od.name, cache_key)
    if fn is not None and cache_key is not None:
        _dc.insert(cache_key, call_fn)
        _dc.record_compile(od.name, cache_key,
                           _time.perf_counter() - _compile_t0,
                           failed=call_fn is fn)

    if _prof_rec is not None:
        _sync = out_vals[0] if multi else out_vals
        if hasattr(_sync, "block_until_ready"):
            _sync.block_until_ready()
        _prof_rec(opname, _prof_t0, _time.perf_counter())

    outs = list(out_vals) if multi else [out_vals]
    if _NAN_CHECK["on"]:
        _check_finite(opname, outs)
    nd_outs = []
    for i, v in enumerate(outs):
        o = NDArray._from_jax(v, out_ctx)
        if out_entries is not None:
            o._ag_entry = out_entries[i]
        nd_outs.append(o)

    if out is not None:
        targets = out if isinstance(out, (list, tuple)) else [out]
        for t, o in zip(targets, nd_outs):
            t._set(o._get().astype(t._get().dtype))
            if out_entries is not None:
                t._ag_entry = o._ag_entry
        return out
    return nd_outs if multi else nd_outs[0]


# flag flipped by symbol-export tracing (symbol/symbol.py trace_invoke) so the
# hot imperative path pays one dict lookup, not an isinstance sweep
_SYMTRACE = {"on": False}

# mixed-precision state, owned by contrib.amp (reference: amp.init()
# monkey-patches op namespaces — here one dict lookup gates the hot path).
# "wrap": callable(opdef, fn) -> fn installed by contrib.amp.  "epoch" is a
# monotonic token bumped on every policy (re)install: the dispatch cache
# keys executables on it so a policy change can never serve stale casts.
_AMP = {"on": False, "wrap": None, "epoch": 0}


def _rng_in_trace(od):
    """True when this needs_rng op draws from a trace-scoped key (inside a
    hybridize/TrainStep trace): the outer jit owns compilation then."""
    if not od.needs_rng:
        return False
    from .. import random as _rnd

    return _rnd._in_trace()

# per-op profiling state, owned by profiler.py ("record": callable(opname,
# t0, t1) installed while profiling imperative ops is enabled)
_PROFILE = {"on": False, "record": None}

# NaN/Inf sanitizer state, owned by engine.set_nan_check (SURVEY.md §6.2:
# the TPU analog of the reference's sanitizer lane — device-side checkify)
_NAN_CHECK = {"on": False}


def _call_with_attrs(fn, attrs, *arrays):
    return fn(*arrays, **attrs)


def _check_finite(opname, vals):
    """NaN/Inf sanitizer (engine.set_nan_check): synchronous check at the
    dispatch seam — the imperative analog of wrapping the program in
    jax.experimental.checkify.  Eager-only: under a trace the values are
    abstract, and the jit path is covered by the loss-finiteness checks."""
    jnp = _jnp()
    import jax

    for v in vals:
        if isinstance(v, jax.core.Tracer) or not hasattr(v, "dtype"):
            continue
        if jnp.issubdtype(v.dtype, jnp.floating) and v.size:
            if not bool(jnp.isfinite(v).all()):
                from ..base import MXNetError

                raise MXNetError(
                    f"nan_check: op {opname!r} produced non-finite values")


def apply_fn(fn, nd_args, name="custom_fn", ctx=None):
    """Run an ad-hoc pure jax function over NDArray inputs with full autograd
    integration — the escape hatch for composite ops (fused RNN scan, pallas
    kernels) that aren't in the registry.  Same tape semantics as invoke()."""
    jnp = _jnp()
    in_vals = []
    out_ctx = ctx
    for a in nd_args:
        if isinstance(a, NDArray):
            in_vals.append(a._get())
            if out_ctx is None:
                out_ctx = a.context
        else:
            in_vals.append(jnp.asarray(a))

    recording = _ag.is_recording() and any(
        isinstance(a, NDArray) and _on_tape(a) for a in nd_args)
    if recording:
        entries = [(a._ag_entry if isinstance(a, NDArray) else None)
                   for a in nd_args]
        out_vals, out_entries, multi = _ag.record_op(fn, in_vals, entries,
                                                     name=name)
    else:
        out_vals = fn(*in_vals)
        multi = isinstance(out_vals, (tuple, list))
        out_entries = None

    outs = list(out_vals) if multi else [out_vals]
    nd_outs = []
    for i, v in enumerate(outs):
        o = NDArray._from_jax(v, out_ctx)
        if out_entries is not None:
            o._ag_entry = out_entries[i]
        nd_outs.append(o)
    return nd_outs if multi else nd_outs[0]


def _on_tape(a):
    return a._ag_entry is not None


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------
def _resolve_dtype(dtype):
    if isinstance(dtype, str) and dtype == "bfloat16":
        return _jnp().bfloat16
    return _np.dtype(dtype) if not isinstance(dtype, type(_jnp().bfloat16)) else dtype


def _sanitize_key(key):
    def conv(k):
        if isinstance(k, NDArray):
            return k._get()
        return k

    if isinstance(key, tuple):
        return tuple(conv(k) for k in key)
    return conv(key)


def _infer_reshape(cur_shape, shape):
    """MXNet reshape specials: 0 = copy dim, -1 = infer, -2..-4 partial.
    Supports 0 and -1 (the overwhelmingly common cases)."""
    size = 1
    for d in cur_shape:
        size *= d
    out = []
    for i, d in enumerate(shape):
        if d == 0:
            out.append(cur_shape[i])
        else:
            out.append(d)
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        out[out.index(-1)] = size // max(known, 1)
    return tuple(out)


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference: mx.nd.array)."""
    jax = _jax()
    jnp = _jnp()
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        v = source_array._get()
    else:
        from_pylist = not hasattr(source_array, "dtype")
        v = _np.asarray(source_array)
        if dtype is None:
            # MXNet default dtype discipline: python lists -> float32;
            # numpy keeps dtype except 64-bit (x64 disabled on the jax side)
            if from_pylist or v.dtype == _np.float64:
                dtype = _np.float32
            elif v.dtype == _np.int64:
                dtype = _np.int32
    if dtype is not None:
        v = _np.asarray(v).astype(_resolve_dtype(dtype)) if not hasattr(v, "astype") else v.astype(_resolve_dtype(dtype))
    if getattr(v, "ndim", 1) == 0:
        # reference semantics: the LEGACY nd namespace has no zero-dim
        # arrays — scalars become shape (1,) — unless npx.set_np(shape=
        # True) is active (mx.np.array is unaffected: numpy semantics are
        # native there)
        from ..numpy_extension import is_np_shape

        if not is_np_shape():
            v = _np.asarray(v).reshape(1)
    out = jax.device_put(jnp.asarray(v), ctx.device)
    return NDArray._from_jax(out, ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return invoke("concat", list(arrays), {"dim": axis})
