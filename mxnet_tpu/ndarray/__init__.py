"""The ``mx.nd`` namespace: functions code-generated from the op table.

Reference: ``python/mxnet/ndarray/register.py`` + ``gen_op`` codegen at
import time from the C op registry (SURVEY.md §3.5 "base/ctypes layer").
Here the registry is ``mxnet_tpu.ops.registry.OP_TABLE``; each op becomes a
module-level function that unwraps NDArrays, dispatches the pure jax fn
(async, ≙ engine push) and wraps results.
"""
from __future__ import annotations

import sys as _sys

import numpy as _np

from .. import ops as _ops  # noqa: F401  (populates the table)
from ..ops.registry import OP_TABLE, list_ops
from ..context import current_context
from .ndarray import NDArray, array, invoke, waitall, concatenate
from . import dispatch_cache as _dispatch_cache

__all__ = ["NDArray", "array", "invoke", "waitall", "zeros", "ones", "full",
           "arange", "empty", "concat", "concatenate", "list_ops", "save", "load",
           "dispatch_stats", "reset_dispatch_stats", "set_eager_jit"]


def dispatch_stats(reset=False):
    """Eager jit-cache counters: hits/misses/evictions/bypasses, cache
    size/capacity, and per-op hit/miss breakdown (see
    ndarray/dispatch_cache.py; knobs: MXNET_EAGER_JIT,
    MXNET_EAGER_JIT_CACHE_SIZE)."""
    out = _dispatch_cache.stats()
    if reset:
        _dispatch_cache.reset_stats()
    return out


def reset_dispatch_stats():
    _dispatch_cache.reset_stats()


def set_eager_jit(flag):
    """Runtime switch for the eager jit-cache fast path (env:
    MXNET_EAGER_JIT).  Returns the previous setting."""
    return _dispatch_cache.set_enabled(flag)


def _make_op_func(opname, od):
    """Positional array inputs map to the op's array params; positional
    scalars/tuples bind (in order) to the op's defaulted attr params —
    mirroring the reference's codegen'd signatures."""
    import inspect

    fn_params = list(inspect.signature(od.fn).parameters.values())
    if od.needs_rng:
        fn_params = fn_params[1:]  # skip the PRNG key param
    attr_names = [p.name for p in fn_params
                  if p.default is not inspect.Parameter.empty]

    def fn(*args, out=None, ctx=None, name=None, **attrs):
        nd_args = []
        extra = []
        for a in args:
            if isinstance(a, NDArray) or type(a).__name__ == "SymbolTracer":
                nd_args.append(a)
            elif isinstance(a, _np.ndarray) or \
                    (hasattr(a, "shape") and hasattr(a, "dtype")):
                nd_args.append(array(a, ctx=ctx))
            else:
                extra.append(a)
        ai = 0
        for v in extra:
            while ai < len(attr_names) and attr_names[ai] in attrs:
                ai += 1
            if ai >= len(attr_names):
                raise TypeError(f"{opname}: too many positional arguments")
            attrs[attr_names[ai]] = v
            ai += 1
        return invoke(opname, nd_args, attrs, out=out, ctx=ctx)

    fn.__name__ = opname
    fn.__qualname__ = opname
    fn.__doc__ = od.fn.__doc__ or f"Operator {opname} (see mxnet_tpu.ops)"
    return fn


_mod = _sys.modules[__name__]
for _name in list(OP_TABLE):
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_op_func(_name, OP_TABLE[_name]))



# user-defined ops (reference: mx.nd.Custom -> src/operator/custom/custom.cc)
from ..operator import custom as Custom  # noqa: E402

# sub-namespaces (reference: python/mxnet/ndarray/{contrib,linalg,image}.py)
from . import contrib  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import image  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from .sparse import RowSparseNDArray, CSRNDArray  # noqa: E402


def cast_storage(arr, stype):
    return arr.tostype(stype)


def sparse_retain(arr, row_ids):
    return sparse.retain(arr, row_ids)


# sparse-aware dot dispatch: csr lhs takes the SpMM path (segment-sum over
# nnz), dense falls through to the registry op (reference: dot FComputeEx)
_dense_dot = dot  # codegen'd above from the op table


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None, **kw):
    if isinstance(lhs, sparse.CSRNDArray) and \
            not isinstance(rhs, sparse._SparseBase):
        res = sparse.dot(lhs, rhs, transpose_a=transpose_a,
                         transpose_b=transpose_b)
        if out is not None:
            out._set(res._get().astype(out._get().dtype))
            return out
        return res
    return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b, out=out, **kw)


# scalar-tolerant binary math (reference: mx.nd.maximum(x, 0) etc. accept
# python scalars on either side).  Scalars dispatch to the registered
# broadcast_*_scalar ops (scalar rides as an attr: no device constant, no
# dtype promotion, output context follows the array operand) — the same
# split the reference's _maximum_scalar path makes.
def _scalar_tolerant(opname, scalar_op):
    base_fn = getattr(_mod, opname)

    def fn(lhs, rhs, *args, out=None, ctx=None, **kw):
        lhs_s = isinstance(lhs, (int, float))
        rhs_s = isinstance(rhs, (int, float))
        if lhs_s and rhs_s:
            res = array(getattr(_np, opname)(
                _np.float32(lhs), _np.float32(rhs)).reshape(()), ctx=ctx)
            if out is not None:
                out._set(res._get().astype(out._get().dtype))
                return out
            return res

        def coerce(scalar, arr):
            # reference semantics: the scalar takes the array's dtype
            # family (int scalar for int arrays), so no weak-type
            # promotion to float32
            if _np.issubdtype(arr.dtype, _np.integer):
                return int(scalar)
            return float(scalar)

        if rhs_s:
            return invoke(scalar_op, [lhs], {"scalar": coerce(rhs, lhs)},
                          out=out, ctx=ctx)
        if lhs_s:
            return invoke(scalar_op, [rhs], {"scalar": coerce(lhs, rhs),
                                             "reverse": True},
                          out=out, ctx=ctx)
        return base_fn(lhs, rhs, *args, out=out, ctx=ctx, **kw)

    fn.__name__ = opname
    fn.__doc__ = base_fn.__doc__
    return fn


for _n in ("maximum", "minimum", "power"):
    setattr(_mod, _n, _scalar_tolerant(_n, f"broadcast_{_n}_scalar"))


# -- convenience overrides with MXNet positional signatures ----------------
def zeros(shape, ctx=None, dtype="float32", **kw):
    return invoke("zeros", [], {"shape": _shape_t(shape), "dtype": dtype}, ctx=ctx)


def ones(shape, ctx=None, dtype="float32", **kw):
    return invoke("ones", [], {"shape": _shape_t(shape), "dtype": dtype}, ctx=ctx)


def full(shape, val, ctx=None, dtype="float32", **kw):
    return invoke("full", [], {"shape": _shape_t(shape), "val": val, "dtype": dtype},
                  ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    return invoke("arange", [], {"start": start, "stop": stop, "step": step,
                                 "repeat": repeat, "dtype": dtype}, ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return invoke("eye", [], {"N": N, "M": M, "k": k, "dtype": dtype}, ctx=ctx)


def concat(*arrays, dim=1, **kw):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("concat", list(arrays), {"dim": dim})


def stack(*arrays, axis=0, **kw):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("stack", list(arrays), {"axis": axis})


def add_n(*arrays, **kw):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("add_n", list(arrays), {})


def zeros_like(a, **kw):
    return invoke("zeros_like", [a], {})


def ones_like(a, **kw):
    return invoke("ones_like", [a], {})


def _shape_t(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


def save(fname, data):
    from .serialization import save as _save

    return _save(fname, data)


def load(fname):
    from .serialization import load as _load

    return _load(fname)


# random namespace: mx.nd.random.uniform(...)
from . import random  # noqa: E402,F401
