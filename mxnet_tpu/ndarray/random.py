"""``mx.nd.random`` namespace (reference: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .ndarray import NDArray, invoke


def _shape_t(shape):
    if shape is None:
        return (1,)
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(low, NDArray) or isinstance(high, NDArray):
        lo = low if isinstance(low, NDArray) else None
        data = lo if lo is not None else high
        return invoke("sample_uniform_like", [data], {"low": float(low) if not isinstance(low, NDArray) else 0.0,
                                                      "high": float(high) if not isinstance(high, NDArray) else 1.0})
    return invoke("random_uniform", [], {"low": low, "high": high,
                                         "shape": _shape_t(shape), "dtype": dtype},
                  out=out, ctx=ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return invoke("random_normal", [], {"loc": loc, "scale": scale,
                                        "shape": _shape_t(shape), "dtype": dtype},
                  out=out, ctx=ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kw):
    return normal(loc, scale, shape=shape or (1,), dtype=dtype, ctx=ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return invoke("random_gamma", [], {"alpha": alpha, "beta": beta,
                                       "shape": _shape_t(shape), "dtype": dtype},
                  out=out, ctx=ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return invoke("random_exponential", [], {"lam": 1.0 / scale,
                                             "shape": _shape_t(shape), "dtype": dtype},
                  out=out, ctx=ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return invoke("random_poisson", [], {"lam": lam, "shape": _shape_t(shape),
                                         "dtype": dtype}, out=out, ctx=ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    return invoke("random_negative_binomial", [], {"k": k, "p": p,
                                                   "shape": _shape_t(shape),
                                                   "dtype": dtype},
                  out=out, ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    return invoke("random_randint", [], {"low": low, "high": high,
                                         "shape": _shape_t(shape), "dtype": dtype},
                  out=out, ctx=ctx)


def multinomial(data, shape=1, get_prob=False, dtype="int32", **kw):
    return invoke("sample_multinomial", [data], {"shape": shape,
                                                 "get_prob": get_prob,
                                                 "dtype": dtype})


def shuffle(data, **kw):
    return invoke("shuffle", [data], {})


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, **kw):
    return invoke("bernoulli", [], {"prob": prob, "shape": _shape_t(shape),
                                    "dtype": dtype}, ctx=ctx)
