"""mx.nd.linalg namespace (reference: python/mxnet/ndarray/linalg.py over
src/operator/tensor/la_op.cc — gemm/potrf/trsm/syrk/det/…)."""
from __future__ import annotations

import sys as _sys

from ..ops.registry import OP_TABLE
from . import _make_op_func

_mod = _sys.modules[__name__]
for _name in list(OP_TABLE):
    if _name.startswith("linalg_"):
        setattr(_mod, _name[len("linalg_"):],
                _make_op_func(_name, OP_TABLE[_name]))
