"""NDArray save/load in the legacy ``.params`` TLV container.

Reference: ``MXNDArraySave/Load`` — a dmlc::Stream TLV container of named
arrays (src/ndarray/ndarray.cc save/load section; SURVEY.md §6.4).  Layout
implemented here (verify byte-level fidelity against the reference when the
mount is populated — SURVEY.md §9.8):

    uint64  kMXAPINDArrayListMagic = 0x112
    uint64  reserved = 0
    uint64  ndarray count N
    N x NDArray records:
        uint32  NDARRAY_V2_MAGIC = 0xF993FAC9
        uint32  reserved (stype = -1 dense)
        uint32  ndim
        uint32  shape[ndim]  (int64 each in V3; V2 uses uint32 — we write V2)
        uint32  context.dev_type, int32 context.dev_id
        int32   type_flag (mshadow enum)
        raw     data bytes (C order)
    uint64  name count (N or 0)
    N x (uint64 len, bytes) names
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array as _nd_array

_LIST_MAGIC = 0x112
_ND_MAGIC = 0xF993FAC9

# mshadow type flags (reference: mshadow/base.h TypeFlag)
_TYPE_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
              "int32": 4, "int8": 5, "int64": 6, "bool": 7, "bfloat16": 12}
_FLAG_TYPE = {v: k for k, v in _TYPE_FLAG.items()}


def _dtype_name(dt):
    s = str(dt)
    return {"<f4": "float32"}.get(s, s)


def save(fname, data):
    """Save NDArrays: dict[str, NDArray], list[NDArray], or single NDArray."""
    if isinstance(data, NDArray):
        names, arrays = [], [data]
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    elif isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        raise MXNetError(f"cannot save {type(data)}")
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQQ", _LIST_MAGIC, 0, len(arrays)))
        for arr in arrays:
            # order="C" (not ascontiguousarray, which silently promotes
            # 0-d arrays to shape (1,)): scalars must round-trip exactly
            np_arr = _np.asarray(arr.asnumpy() if isinstance(arr, NDArray)
                                 else arr, order="C")
            dt = _dtype_name(np_arr.dtype.name if hasattr(np_arr.dtype, "name")
                             else np_arr.dtype)
            if dt not in _TYPE_FLAG:
                # bfloat16 comes through as 'bfloat16' via ml_dtypes
                raise MXNetError(f"unsupported dtype {dt}")
            f.write(struct.pack("<II", _ND_MAGIC, 0xFFFFFFFF))
            f.write(struct.pack("<I", np_arr.ndim))
            f.write(struct.pack(f"<{np_arr.ndim}I", *np_arr.shape) if np_arr.ndim else b"")
            f.write(struct.pack("<Ii", 1, 0))  # cpu context
            f.write(struct.pack("<i", _TYPE_FLAG[dt]))
            f.write(np_arr.tobytes())
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname):
    """Load NDArrays saved by :func:`save`. Returns dict (if named) or list."""
    with open(fname, "rb") as f:
        magic, _res, count = struct.unpack("<QQQ", f.read(24))
        if magic != _LIST_MAGIC:
            raise MXNetError(f"invalid .params file {fname} (magic {magic:#x})")
        arrays = []
        for _ in range(count):
            nd_magic, stype = struct.unpack("<II", f.read(8))
            if nd_magic != _ND_MAGIC:
                raise MXNetError("corrupt NDArray record")
            (ndim,) = struct.unpack("<I", f.read(4))
            shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim)) if ndim else ()
            _devt, _devid = struct.unpack("<Ii", f.read(8))
            (tflag,) = struct.unpack("<i", f.read(4))
            dt = _FLAG_TYPE[tflag]
            if dt == "bfloat16":
                import ml_dtypes

                np_dt = _np.dtype(ml_dtypes.bfloat16)
            else:
                np_dt = _np.dtype(dt)
            nbytes = int(_np.prod(shape)) * np_dt.itemsize if shape else np_dt.itemsize
            buf = f.read(nbytes)
            np_arr = _np.frombuffer(buf, dtype=np_dt).reshape(shape)
            # bypass mx.nd.array: deserialization must reproduce the
            # stored shape EXACTLY (nd.array promotes 0-d scalars to (1,)
            # under legacy np_shape-off semantics)
            import jax.numpy as _jnp_

            arrays.append(NDArray._from_jax(_jnp_.asarray(np_arr), None))
        (n_names,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays
