"""Sparse NDArray: row_sparse and csr storage (``mx.nd.sparse``).

Reference: ``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray /
CSRNDArray, ~1.5k lines over the C++ storage-type machinery in
include/mxnet/ndarray.h — SURVEY.md §3.1/§3.5).

TPU-native design: sparse tensors are COORDINATE-STRUCTURED pairs of dense
jax arrays (indices + values), because XLA has no native sparse layout —
gathers/scatters over dense blocks ARE the TPU sparse idiom.  The dense
fallback (materialize, run the dense op) mirrors the reference's own
behavior for ops without FComputeEx.  The row_sparse path is what matters
for BASELINE config #4: embedding-style gradients carry only touched rows
through KVStore push/pull and optimizer updates scatter only those rows.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "array"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class _SparseBase(NDArray):
    """Common machinery: dense materialization through ``_get`` so every
    dense op transparently accepts sparse inputs (reference: storage
    fallback), while sparse-aware consumers read the compact parts."""

    __slots__ = ()

    @property
    def stype(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == "default":
            return NDArray._from_jax(self._get(), self.context)
        if stype == self.stype:
            return self
        if stype == "row_sparse":
            return RowSparseNDArray.from_dense(self._get(), self.context)
        if stype == "csr":
            return CSRNDArray.from_dense(self._get(), self.context)
        raise MXNetError(f"unknown stype {stype!r}")

    def copy(self):
        return self.tostype(self.stype)


class RowSparseNDArray(_SparseBase):
    """(indices (K,), values (K, *cols)) representing shape (N, *cols);
    rows not listed are zero."""

    __slots__ = ("_rs_indices", "_rs_values", "_rs_shape")

    @classmethod
    def create(cls, indices, values, shape, ctx=None):
        jnp = _jnp()
        self = cls._new()
        self._rs_indices = jnp.asarray(indices, dtype=jnp.int32)
        self._rs_values = jnp.asarray(values)
        self._rs_shape = tuple(shape)
        from ..context import current_context

        self._ctx = ctx or current_context()
        self._data = None
        return self

    @classmethod
    def from_dense(cls, dense, ctx=None):
        jnp = _jnp()
        dense = jnp.asarray(dense)
        nz = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        idx = jnp.nonzero(nz)[0]
        return cls.create(idx, dense[idx], dense.shape, ctx)

    # -- NDArray surface ---------------------------------------------------
    def _get(self):
        jnp = _jnp()
        if self._data is not None:
            return self._data
        dense = jnp.zeros(self._rs_shape, dtype=self._rs_values.dtype)
        if self._rs_values.shape[0]:
            dense = dense.at[self._rs_indices].set(self._rs_values)
        return dense

    def _set(self, value):
        raise MXNetError("RowSparseNDArray is immutable; convert with "
                         "tostype('default') first")

    @property
    def shape(self):
        return self._rs_shape

    @property
    def dtype(self):
        return self._rs_values.dtype

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        return NDArray._from_jax(self._rs_indices, self._ctx)

    @property
    def data(self):
        return NDArray._from_jax(self._rs_values, self._ctx)

    def __repr__(self):
        return (f"<RowSparseNDArray {self._rs_shape} "
                f"({self._rs_values.shape[0]} rows stored)>")

    def retain(self, row_ids):
        """Keep only the requested rows (reference: sparse_retain op)."""
        jnp = _jnp()
        rid = row_ids._get() if isinstance(row_ids, NDArray) else \
            jnp.asarray(row_ids)
        rid = rid.astype(jnp.int32)
        keep = jnp.isin(self._rs_indices, rid)
        idx = _np.asarray(self._rs_indices)[_np.asarray(keep)]
        vals = _np.asarray(self._rs_values)[_np.asarray(keep)]
        return RowSparseNDArray.create(idx, vals, self._rs_shape, self._ctx)


class CSRNDArray(_SparseBase):
    """Compressed sparse row matrix: (data, indices, indptr) + 2-D shape."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr", "_csr_shape")

    @classmethod
    def create(cls, data, indices, indptr, shape, ctx=None):
        jnp = _jnp()
        self = cls._new()
        self._csr_data = jnp.asarray(data)
        self._csr_indices = jnp.asarray(indices, dtype=jnp.int32)
        self._csr_indptr = jnp.asarray(indptr, dtype=jnp.int32)
        self._csr_shape = tuple(shape)
        from ..context import current_context

        self._ctx = ctx or current_context()
        self._data = None
        return self

    @classmethod
    def from_dense(cls, dense, ctx=None):
        d = _np.asarray(dense)
        if d.ndim != 2:
            raise MXNetError("csr storage requires a 2-D array")
        rows, cols = _np.nonzero(d)
        data = d[rows, cols]
        indptr = _np.zeros(d.shape[0] + 1, dtype=_np.int64)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        return cls.create(data, cols, indptr, d.shape, ctx)

    def _get(self):
        jnp = _jnp()
        if self._data is not None:
            return self._data
        n, m = self._csr_shape
        dense = jnp.zeros((n, m), dtype=self._csr_data.dtype)
        if self._csr_data.shape[0]:
            counts = jnp.diff(self._csr_indptr)
            rows = jnp.repeat(jnp.arange(n), counts,
                              total_repeat_length=self._csr_data.shape[0])
            dense = dense.at[rows, self._csr_indices].set(self._csr_data)
        return dense

    def _set(self, value):
        raise MXNetError("CSRNDArray is immutable; convert with "
                         "tostype('default') first")

    @property
    def shape(self):
        return self._csr_shape

    @property
    def dtype(self):
        return self._csr_data.dtype

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray._from_jax(self._csr_data, self._ctx)

    @property
    def indices(self):
        return NDArray._from_jax(self._csr_indices, self._ctx)

    @property
    def indptr(self):
        return NDArray._from_jax(self._csr_indptr, self._ctx)

    def __repr__(self):
        return (f"<CSRNDArray {self._csr_shape} "
                f"({self._csr_data.shape[0]} stored)>")


# --------------------------------------------------------------------------
# constructors (reference: mx.nd.sparse.*)
# --------------------------------------------------------------------------
def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 2:
        values, indices = arg
        if shape is None:
            raise MXNetError("shape required for (data, indices) input")
        return RowSparseNDArray.create(indices, values, shape, ctx)
    if isinstance(arg, RowSparseNDArray):
        return arg
    dense = arg.asnumpy() if isinstance(arg, NDArray) else _np.asarray(arg)
    if dtype is not None:
        dense = dense.astype(dtype)
    return RowSparseNDArray.from_dense(dense, ctx)


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise MXNetError("shape required for (data, indices, indptr)")
        return CSRNDArray.create(data, indices, indptr, shape, ctx)
    if isinstance(arg, CSRNDArray):
        return arg
    dense = arg.asnumpy() if isinstance(arg, NDArray) else _np.asarray(arg)
    if dtype is not None:
        dense = dense.astype(dtype)
    return CSRNDArray.from_dense(dense, ctx)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        cols = shape[1:]
        return RowSparseNDArray.create(
            _np.zeros((0,), dtype=_np.int64),
            _np.zeros((0,) + tuple(cols), dtype=dtype), shape, ctx)
    if stype == "csr":
        return CSRNDArray.create(
            _np.zeros((0,), dtype=dtype), _np.zeros((0,), dtype=_np.int64),
            _np.zeros(shape[0] + 1, dtype=_np.int64), shape, ctx)
    from . import zeros as _dzeros

    return _dzeros(shape, ctx=ctx, dtype=dtype)


def array(source, ctx=None, dtype=None):
    if isinstance(source, (RowSparseNDArray, CSRNDArray)):
        return source
    return _dense_array(source, ctx=ctx)


# --------------------------------------------------------------------------
# sparse-aware helpers (reference: FComputeEx kernels)
# --------------------------------------------------------------------------
def add_rowsparse(a, b):
    """Sparse-sparse add keeping row_sparse storage (reference:
    elemwise_add FComputeEx rsp+rsp)."""
    ai = _np.asarray(a._rs_indices)
    bi = _np.asarray(b._rs_indices)
    av = _np.asarray(a._rs_values)
    bv = _np.asarray(b._rs_values)
    union = _np.union1d(ai, bi)
    vals = _np.zeros((len(union),) + av.shape[1:], dtype=av.dtype)
    vals[_np.searchsorted(union, ai)] += av
    vals[_np.searchsorted(union, bi)] += bv
    return RowSparseNDArray.create(union, vals, a.shape, a._ctx)


def _spmm(data, cols, indptr, n_rows, n_cols, dn, transpose_a):
    """Pure-jax SpMM kernel: gather the needed dense rows per nonzero and
    segment-sum — no dense materialization of the csr operand."""
    import jax

    jnp = _jnp()
    vec = dn.ndim == 1
    dn2 = dn[:, None] if vec else dn  # 1-D rhs: matvec via a (k, 1) matmul
    nnz = data.shape[0]
    counts = jnp.diff(indptr)
    rows = jnp.repeat(jnp.arange(n_rows), counts, total_repeat_length=nnz)
    if not transpose_a:
        # out[r] += data * dense[col]
        contrib = data[:, None] * dn2[cols]
        out = jax.ops.segment_sum(contrib, rows, num_segments=n_rows)
    else:
        # out[col] += data * dense[row]  (shape (m, k))
        contrib = data[:, None] * dn2[rows]
        out = jax.ops.segment_sum(contrib, cols, num_segments=n_cols)
    return out[:, 0] if vec else out


def dot_csr_dense(csr, dense, transpose_a=False):
    """csr × dense matmul (reference: src/operator/tensor/dot.cc csr paths).

    Autograd: routed through apply_fn so the gradient flows to the dense
    operand (grad wrt the dense rhs is csrᵀ × out_grad — jax derives it from
    the same segment-sum program).  Gradient wrt the csr *values* is not
    supported, matching the reference csr dot which treats the sparse
    operand as data."""
    from .ndarray import apply_fn

    if not isinstance(dense, NDArray) and not hasattr(dense, "shape"):
        dense = _jnp().asarray(dense)
    want = csr._csr_shape[0] if transpose_a else csr._csr_shape[1]
    if dense.shape[0] != want:
        # jax clamps out-of-bounds gathers, which would return silently
        # wrong values — fail like the dense path does
        raise MXNetError(
            f"dot: csr shape {csr._csr_shape} (transpose_a={transpose_a}) "
            f"incompatible with rhs shape {tuple(dense.shape)}")
    data = csr._csr_data
    cols = csr._csr_indices
    indptr = csr._csr_indptr
    n_rows, n_cols = csr._csr_shape

    def fn(dn):
        return _spmm(data, cols, indptr, n_rows, n_cols, dn, transpose_a)

    return apply_fn(fn, [dense], name="dot_csr_dense", ctx=csr._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Storage-dispatching dot (reference: mx.nd.sparse.dot)."""
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, _SparseBase):
        if transpose_b:
            raise MXNetError("transpose_b unsupported for csr dot")
        return dot_csr_dense(lhs, rhs, transpose_a=transpose_a)
    # fall back to the registry op directly (densifies via _get); going
    # through the module-level mx.nd.dot wrapper would recurse
    from .ndarray import invoke

    return invoke("dot", [lhs, rhs], {"transpose_a": transpose_a,
                                      "transpose_b": transpose_b})


def cast_storage(arr, stype):
    """Reference: src/operator/tensor/cast_storage.cc."""
    return arr.tostype(stype)


def retain(arr, row_ids):
    """Reference: sparse_retain op."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain requires a RowSparseNDArray")
    return arr.retain(row_ids)
