"""Eager dispatch fast path: jit-cached op executables (SURVEY.md §8 hard
part 5, VERDICT r5 Weak #9).

The reference engine amortizes per-op imperative cost through CachedOp and
engine bulk dispatch (SURVEY.md §4.1/§4.6); TVM makes the same observation
that per-op *launch* overhead, not kernel time, dominates small-op
workloads.  The TPU build's analog: every registry-op call from
``ndarray.invoke`` compiles once into a ``jax.jit`` executable keyed on

    (opname, static attrs, input avals, AMP state, ctx kind, train mode)

and is served from a bounded LRU thereafter — repeat calls skip per-primitive
eager dispatch entirely and go through jit's C++ fast path.

Compatibility contract (the cache must never *break* an op):
- ops whose Python body cannot be traced (value-dependent control flow,
  host-side numpy on values) fail once at compile time, fall back to eager
  execution, and land on a per-op blocklist so they never pay tracing again;
- ops may opt out statically with ``register(..., jit_safe=False)``
  (per-``OpDef`` staticness metadata) — e.g. flash attention re-reads its
  block-size env vars per call;
- unhashable attrs, tracer inputs (an outer jit/hybridize trace is already
  compiling), and ``MXNET_ENGINE_TYPE=NaiveEngine`` bypass the cache.

Observability: global hit/miss/evict/bypass counters plus per-op
hit/miss/bypass attribution, exposed via ``mx.nd.dispatch_stats()`` and the
profiler's per-op table.  Env knobs:
``MXNET_EAGER_JIT={0,1}`` (default 1) and ``MXNET_EAGER_JIT_CACHE_SIZE``
(default 1024 executables).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

from .. import env as _env

__all__ = ["enabled", "set_enabled", "set_capacity", "capacity", "lookup",
           "insert", "make_key", "signature_key", "mark_unsafe", "stats",
           "reset_stats", "clear"]

_LOCK = threading.Lock()
_CACHE = OrderedDict()          # key -> jitted callable (LRU: last = newest)
_BLOCKLIST = set()              # opnames with >=1 trace failure (reporting)
_FAILED_KEYS = {}               # opname -> set of DISTINCT failing keys
_FAIL_COUNTS = {}               # opname -> keyless trace failures (legacy)
_OP_BLOCK_AFTER = 3             # stop re-trying jit for an op past this
_STATS = {"hits": 0, "misses": 0, "evictions": 0, "bypasses": 0}
_PER_OP = {}                    # opname -> [hits, misses, bypasses]
# compile-cause tracking: per op, the attrs-keys / shapes / dtypes / mode
# tokens already compiled — a fresh compile's cause is the first component
# that is new (telemetry compile-event tracer).  Each per-op set is capped
# (a variable-shape retrace storm — the exact workload the tracer exists
# to diagnose — must not leak memory proportional to distinct shapes):
# past the cap new tokens still classify correctly, they are just not
# remembered, so a later repeat re-reports its new_* cause.
_COMPILE_SEEN = {}
_COMPILE_SEEN_CAP = 4096

_CFG = {
    "on": _env.get_bool("MXNET_EAGER_JIT", True),
    "capacity": max(1, _env.get_int("MXNET_EAGER_JIT_CACHE_SIZE", 1024)),
    # set while MXNET_ENGINE_TYPE=NaiveEngine: deterministic op-by-op eager
    # execution must not be served from fused executables
    "engine_bypass": False,
}

# simple attr value types that hash stably and cannot alias array data
_HASHABLE_SCALARS = (bool, int, float, complex, str, bytes, type(None))


def enabled():
    return _CFG["on"] and not _CFG["engine_bypass"]


def set_enabled(flag):
    """Runtime switch for the jit fast path (env: MXNET_EAGER_JIT)."""
    prev = _CFG["on"]
    _CFG["on"] = bool(flag)
    return prev


def set_engine_bypass(flag):
    """Engine-level bypass (NaiveEngine: deterministic op-by-op eager)."""
    _CFG["engine_bypass"] = bool(flag)


def capacity():
    return _CFG["capacity"]


def set_capacity(n):
    """Resize the executable LRU (env: MXNET_EAGER_JIT_CACHE_SIZE)."""
    n = max(1, int(n))
    with _LOCK:
        _CFG["capacity"] = n
        while len(_CACHE) > n:
            _CACHE.popitem(last=False)
            _STATS["evictions"] += 1


def _attrs_key(attrs):
    """Hashable key for a static-attrs dict, or None if any value is not a
    simple static type (then the call bypasses the cache).  Keyed in dict
    order: the same call site always produces the same order, and a
    different-order duplicate only costs one extra (correct) entry."""
    items = []
    for k, v in attrs.items():
        v = _freeze(v)
        if v is _UNHASHABLE:
            return None
        items.append((k, v))
    return tuple(items)


_UNHASHABLE = object()


def _freeze(v):
    if isinstance(v, _HASHABLE_SCALARS):
        # (type, repr) and not the value itself: Python hashes 0.0 == -0.0
        # == False and 2 == 2.0 == True equal, but they compile to
        # different constants (signbit!) / dtypes — a raw-value key would
        # serve the wrong executable.  repr also makes nan keys self-equal
        # so a nan attr can still hit.
        return (type(v).__name__, repr(v))
    if isinstance(v, (tuple, list)):
        out = tuple(_freeze(x) for x in v)
        return _UNHASHABLE if _UNHASHABLE in out else out
    # np.dtype / jnp dtype objects hash stably; arrays and everything else
    # bypass (an array attr could alias data the executable would freeze)
    import numpy as _np

    if isinstance(v, _np.dtype) or (isinstance(v, type)
                                    and issubclass(v, _np.generic)):
        return str(v)
    return _UNHASHABLE


_TRACER = None  # lazy jax.core.Tracer (jax must not load at module import)


def make_key(opname, attrs, in_vals, amp_token, ctx_kind, training,
             stats_name=None):
    """Full cache key, or None when this call must bypass (unhashable attrs
    or tracer inputs).  Counts the bypass under ``stats_name`` (the
    call-site op name; ``opname`` is the canonical name keyed into the
    cache so aliases share executables).

    Avals are (shape, dtype) only — finer distinctions (weak types, x64
    flips) are disambiguated by jit's own internal signature cache, so a
    coarser key here can merge entries but never serve a wrong executable.
    """
    sn = stats_name or opname
    akey = _attrs_key(attrs)
    if akey is None:
        count_bypass(sn)
        return None
    global _TRACER
    if _TRACER is None:
        import jax

        _TRACER = jax.core.Tracer
    avals = []
    for v in in_vals:
        if isinstance(v, _TRACER):
            # already under an outer trace (hybridize/TrainStep/vjp replay):
            # the outer jit owns compilation
            count_bypass(sn)
            return None
        try:
            avals.append((v.shape, v.dtype))
        except Exception:
            count_bypass(sn)
            return None
    return (opname, akey, tuple(avals), amp_token, ctx_kind, bool(training))


def signature_key(name, in_vals, extra=()):
    """AOT-executable key with the eager fast path's keying discipline.

    The serving engine (:mod:`mxnet_tpu.serving`) pre-compiles its
    prefill/decode/sample executables per bucketed signature and must
    serve steady state with ZERO fresh traces — the same contract the
    LRU above enforces per op.  Sharing the key construction (aval
    components + frozen static extras + AMP epoch + ctx kind) means a
    change that would retrace here (new shape/dtype, AMP epoch flip,
    context move) is exactly one that misses there, so the PR 3 compile
    tracer sees both worlds through one vocabulary.

    ``in_vals`` may be arrays or ``jax.ShapeDtypeStruct``s; ``extra`` is
    a tuple of simple static scalars (bucket ids, phase names).  Unlike
    :func:`make_key` there is no bypass path: an unhashable component is
    a caller bug and raises."""
    items = tuple(_freeze(v) for v in extra)
    if _UNHASHABLE in items:
        raise ValueError(
            f"signature_key({name!r}): unhashable static component in "
            f"{extra!r}")
    avals = tuple((tuple(v.shape), str(v.dtype)) for v in in_vals)
    from .ndarray import _AMP
    from ..context import current_context

    amp_token = _AMP["epoch"] if _AMP["on"] else None
    ctx = current_context()
    return (name, items, avals, amp_token,
            ctx.device_type if ctx is not None else None)


def is_blocked(opname):
    """True once an op has failed to trace on several DISTINCT keys —
    attrs-specific failures keep the fast path for the op's other
    variants (their failing keys get an eager entry instead)."""
    return (len(_FAILED_KEYS.get(opname, ())) +
            _FAIL_COUNTS.get(opname, 0)) >= _OP_BLOCK_AFTER


def mark_unsafe(opname, key=None):
    """Record a trace failure for ``opname`` and warn once per op.  The
    failing (op, attrs, avals) key itself gets the eager fn cached in its
    LRU slot by the caller, so only failures on DISTINCT keys escalate to
    blocking the whole op: ``key`` identifies the failing variant, and
    re-failures of an already-recorded key (its eager entry was LRU-
    evicted and the retrace failed again) do not count toward the block
    threshold (ROADMAP open item: eviction-driven re-failures of one
    variant must not falsely blocklist a whole op).  Callers without a
    key (legacy/tests) fall back to a per-op counter."""
    with _LOCK:
        fresh = opname not in _BLOCKLIST
        _BLOCKLIST.add(opname)
        if key is None:
            _FAIL_COUNTS[opname] = _FAIL_COUNTS.get(opname, 0) + 1
        else:
            _FAILED_KEYS.setdefault(opname, set()).add(key)
    if fresh:
        import warnings

        warnings.warn(
            f"mxnet_tpu: op {opname!r} failed to jit-compile and runs "
            "eagerly (see mx.nd.dispatch_stats()['blocklisted'])",
            stacklevel=3)


def record_compile(opname, key, elapsed_s, failed=False):
    """Telemetry hook for a fresh compile on the invoke seam.  ``key`` is
    the full cache key; the cause is derived from which component of it is
    new for this op (shape/dtype/attrs/mode), so retrace storms name their
    driver.  Called only on the miss path — hits never reach here."""
    if failed:
        cause = "trace_failure"
    else:
        shapes = tuple(a[0] for a in key[2])
        dtypes = tuple(str(a[1]) for a in key[2])
        mode = key[3:]
        with _LOCK:
            seen = _COMPILE_SEEN.get(opname)
            if seen is None:
                _COMPILE_SEEN[opname] = {"akeys": {key[1]},
                                         "shapes": {shapes},
                                         "dtypes": {dtypes},
                                         "modes": {mode}}
                cause = "new_op"
            else:
                if dtypes not in seen["dtypes"]:
                    cause = "new_dtype"
                elif shapes not in seen["shapes"]:
                    cause = "new_shape"
                elif key[1] not in seen["akeys"]:
                    cause = "new_attrs"
                elif mode not in seen["modes"]:
                    cause = "mode_change"   # AMP epoch / ctx / train flip
                else:
                    cause = "recompile"     # LRU-evicted entry re-traced
                for s, token in ((seen["akeys"], key[1]),
                                 (seen["shapes"], shapes),
                                 (seen["dtypes"], dtypes),
                                 (seen["modes"], mode)):
                    if len(s) < _COMPILE_SEEN_CAP:
                        s.add(token)
    from .. import telemetry

    telemetry.compile_event("op", opname, elapsed_s, cause)


def _per_op(opname):
    per = _PER_OP.get(opname)
    if per is None:
        per = _PER_OP[opname] = [0, 0, 0]
    return per


def lookup(opname, key):
    """Cached executable for ``key`` or None.  Counts hit/miss per op."""
    with _LOCK:
        fn = _CACHE.get(key)
        per = _per_op(opname)
        if fn is not None:
            _CACHE.move_to_end(key)
            _STATS["hits"] += 1
            per[0] += 1
        else:
            _STATS["misses"] += 1
            per[1] += 1
        return fn


def insert(key, fn):
    with _LOCK:
        _CACHE[key] = fn
        _CACHE.move_to_end(key)
        while len(_CACHE) > _CFG["capacity"]:
            _CACHE.popitem(last=False)
            _STATS["evictions"] += 1


def count_bypass(opname=None):
    with _LOCK:
        _STATS["bypasses"] += 1
        if opname is not None:
            _per_op(opname)[2] += 1


def stats():
    """Counters snapshot (surfaced as ``mx.nd.dispatch_stats()``)."""
    with _LOCK:
        return {
            "enabled": enabled(),
            "size": len(_CACHE),
            "capacity": _CFG["capacity"],
            "hits": _STATS["hits"],
            "misses": _STATS["misses"],
            "evictions": _STATS["evictions"],
            "bypasses": _STATS["bypasses"],
            "blocklisted": sorted(_BLOCKLIST),
            "trace_failures": {
                name: len(_FAILED_KEYS.get(name, ()))
                + _FAIL_COUNTS.get(name, 0)
                for name in sorted(set(_FAILED_KEYS) | set(_FAIL_COUNTS))},
            "per_op": {name: {"hits": c[0], "misses": c[1], "bypasses": c[2]}
                       for name, c in sorted(_PER_OP.items())},
        }


def reset_stats():
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0
        _PER_OP.clear()
        _COMPILE_SEEN.clear()


def clear():
    """Drop all cached executables (stats and blocklist survive)."""
    with _LOCK:
        _CACHE.clear()
