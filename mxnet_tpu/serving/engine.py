"""AOT-compiled serving engine: continuous batching over paged decode.

The inference analog of TrainStep.  One engine owns one model's frozen
weights, a :class:`~mxnet_tpu.serving.kvcache.PagedKVCache`, and a table
of **ahead-of-time compiled** executables — prefill per prompt-length
bucket, decode per (batch bucket, page bucket), sampling per batch
bucket — built once at :meth:`start` and looked up thereafter with the
PR 1 dispatch-cache keying (``dispatch_cache.signature_key``).  The
steady-state loop therefore performs **zero fresh traces**: every
request is padded up to a bucketed signature that already has an
executable, and the PR 3 compile tracer (kind ``serving``) proves it —
after warmup the compile counter must not move.

Loop shape (one iteration = one engine step):

1. **admit** — pop waiting requests (deadline-expired ones resolve with
   a clean error), allocate KV pages (evicting the youngest active
   sequence back to the queue if the pool is short), run the bucketed
   prefill executable, sample the first token.
2. **decode** — one batched single-token step for every active
   sequence: rows at arbitrary positions share one executable call
   (join/leave per step), new k/v is scattered into each row's pages,
   logits are sampled (greedy or keyed temperature) and the ONE host
   sync per step fetches the tokens.
3. **retire** — finished sequences (max tokens / EOS / context cap)
   free their pages and resolve their futures.

Shutdown honors the PR 5 lifecycle contract: a SIGTERM (or
``close(drain=True)``) stops admission, lets in-flight sequences
finish, rejects queued work with a clean error, and :func:`serve` exits
with ``lifecycle.EXIT_PREEMPTED``.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

import numpy as _np

from .. import compile_cache as _ccache
from .. import env as _env
from .. import fault as _fault
from .. import introspection as _introspection
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..ndarray import dispatch_cache as _dc
from .kvcache import PagedKVCache, pages_for
from .scheduler import (AdmissionQueue, DeadlineExceededError, Request,
                        bucket_for, parse_buckets)

__all__ = ["ServingEngine", "serve"]

_LOGGER = logging.getLogger(__name__)


# -- metric families (registered once; recording is always-on) -------------
_G_QUEUE = _telemetry.gauge(
    "mxnet_serving_queue_depth", "requests waiting for admission")
_G_ACTIVE = _telemetry.gauge(
    "mxnet_serving_active_sequences", "sequences in the decode batch")
_H_OCCUPANCY = _telemetry.histogram(
    "mxnet_serving_batch_occupancy",
    "decode-batch fill ratio (active rows / padded bucket rows)",
    buckets=[0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0])
_H_PHASE = _telemetry.histogram(
    "mxnet_serving_phase_seconds",
    "serving step time by phase (prefill includes the first-token "
    "sample; decode includes sampling + the per-step token fetch)",
    labelnames=("phase",))
_H_LATENCY = _telemetry.histogram(
    "mxnet_serving_request_seconds", "request latency, submit -> done")
_H_TTFT = _telemetry.histogram(
    "mxnet_serving_ttft_seconds", "time to first token")
_C_TOKENS = _telemetry.counter(
    "mxnet_serving_tokens_total", "tokens processed",
    labelnames=("kind",))
_C_REQS = _telemetry.counter(
    "mxnet_serving_requests_total", "finished requests by outcome",
    labelnames=("outcome",))
_C_EVICT = _telemetry.counter(
    "mxnet_serving_evictions_total",
    "sequences evicted from the KV pool back to the queue")
_G_PAGES = _telemetry.gauge(
    "mxnet_serving_kv_pages", "KV-cache pool pages",
    labelnames=("state",))
_G_TOKS_S = _telemetry.gauge(
    "mxnet_serving_tokens_per_s",
    "generated tokens/s over the trailing window")
_G_TOKS_CHIP = _telemetry.gauge(
    "mxnet_tokens_per_s_per_chip",
    "generated tokens/s per device over the trailing window (the "
    "serving half of online utilization accounting)")
_H_JOIN = _telemetry.histogram(
    "mxnet_serving_join_to_first_token_seconds",
    "replica handoff: wall time from joining (params donated by a "
    "running engine) to this replica's first generated token")
_C_STEP_FAIL = _telemetry.counter(
    "mxnet_serving_step_failures_total",
    "engine-loop steps that raised and were absorbed (incl. injected "
    "serving.decode_step faults) — the loop retries, state untorn")


class _Seq:
    """One active sequence: its request plus cache bookkeeping.

    ``cache_len`` counts tokens whose k/v live in the pool; the next
    decode step feeds ``last_token`` at position ``cache_len`` (its k/v
    is written by that step)."""

    __slots__ = ("req", "cache_len", "last_token", "joined")

    def __init__(self, req, cache_len, last_token, joined):
        self.req = req
        self.cache_len = cache_len
        self.last_token = last_token
        self.joined = joined


class ServingEngine:
    """Continuous-batching inference engine for the llama model zoo.

    ``net`` is an initialized (non-MoE) ``LlamaForCausalLM``; its
    parameters are snapshotted at construction (frozen-weights
    deployment semantics — a served model does not train).  All bucket
    grids default from the ``MXNET_SERVING_*`` knobs (see env.py and
    the README "Serving" section)."""

    def __init__(self, net, *, batch_buckets=None, prefill_buckets=None,
                 kv_pages=None, page_size=None, queue_bound=None,
                 max_batch=None, deadline_ms=None, name=None, plan=None,
                 params_from=None, compile_cache=None,
                 trace_requests=None):
        from ..gluon.model_zoo.language.llama import (LlamaForCausalLM,
                                                      serving_params)

        if not isinstance(net, LlamaForCausalLM):
            raise MXNetError("ServingEngine serves the model-zoo llama "
                             f"family, got {type(net).__name__}")
        cfg = net.config
        if cfg.num_experts > 0:
            raise MXNetError("incremental decode does not support MoE "
                             "FFNs yet (prefill/decode_apply contract)")
        self._cfg = cfg
        self._name = name or "llama"
        # replica handoff skips this entirely: the donated params below
        # ARE the weights, and the join-to-first-token path must not
        # pay a second materialization from the net
        self._params = {} if params_from is not None else \
            dict(serving_params(net))
        # tensor-parallel serving (ROADMAP serving follow-on (a)): a
        # ShardingPlan places the frozen params once at construction and
        # every prefill/decode/sample executable AOT-compiles against
        # the sharded avals — steady state still performs zero fresh
        # traces, GSPMD owns the collectives.  plan=None keeps the
        # single-device layout bit-for-bit.
        self._plan = plan
        self._serve_mesh = None
        self._rep_sharding = None
        # warm-start compile cache (explicit > MXNET_COMPILE_CACHE_DIR
        # session default > none): a warm engine start loads every AOT
        # executable instead of tracing it — zero compile events
        self._cc = _ccache.resolve(compile_cache)
        # replica handoff (join_replica): a RUNNING donor engine hands
        # its frozen params over through the live-resharding transfer
        # (donor plan -> this plan) while it keeps serving — its param
        # arrays are immutable, the transfer only reads them.  The
        # join-to-first-token clock starts here.
        self._join_t0 = None
        if params_from is not None:
            from ..parallel import resharding as _resharding

            self._params = _resharding.transfer_params(
                dict(params_from._params), src_plan=params_from._plan,
                tgt_plan=plan)
            self._join_t0 = time.monotonic()
        if plan is not None:
            import jax

            self._serve_mesh = plan.build_mesh()
            self._rep_sharding = plan.replicated(self._serve_mesh)
            if params_from is None:
                self._params = {
                    k: jax.device_put(v,
                                      plan.sharding(k, self._serve_mesh))
                    for k, v in self._params.items()}
        # bucket grids + page size resolve through the tuning funnel
        # (explicit ctor args > env pins > MXNET_TUNE=1 stored winners
        # keyed by this engine's plan digest > defaults); the env
        # accessors remain the fallback so serving never depends on
        # the tuning tier
        _pd = plan.digest() if plan is not None else None
        try:
            from .. import tuning as _tuning

            _t_batch = str(_tuning.resolve("serving_batch_buckets",
                                           plan_digest=_pd))
            _t_prefill = str(_tuning.resolve("serving_prefill_buckets",
                                             plan_digest=_pd))
            _t_page = int(_tuning.resolve("serving_page_size",
                                          plan_digest=_pd))
        except Exception:
            _t_batch = _env.serving_batch_buckets()
            _t_prefill = _env.serving_prefill_buckets()
            _t_page = _env.serving_page_size()
        self._batch_buckets = list(batch_buckets) if batch_buckets else \
            parse_buckets(_t_batch, "batch bucket")
        self._prefill_buckets = list(prefill_buckets) if prefill_buckets \
            else parse_buckets(_t_prefill, "prefill bucket")
        self._page_size = int(page_size or _t_page)
        pages = int(kv_pages or _env.serving_kv_pages())
        self._max_batch = int(max_batch or _env.serving_max_batch())
        if self._max_batch > max(self._batch_buckets):
            raise MXNetError(
                f"max_batch {self._max_batch} exceeds the largest batch "
                f"bucket {max(self._batch_buckets)} — every admitted "
                "batch must fit a pre-compiled signature")
        self._deadline_ms = deadline_ms if deadline_ms is not None else \
            _env.serving_deadline_ms()
        dt = str(net.model.embed_tokens.weight.data().dtype)
        self._kv = PagedKVCache(cfg.num_layers, cfg.num_kv_heads,
                                cfg.head_dim, pages, self._page_size,
                                dtype=dt)
        # longest context a sequence can reach: the model's window, the
        # pool minus scratch, and the largest decode page bucket all cap it
        self._ctx_cap = min(cfg.max_seq_len, (pages - 1) * self._page_size)
        self._page_buckets = self._make_page_buckets()
        if max(self._prefill_buckets) > self._ctx_cap:
            raise MXNetError(
                f"prefill bucket {max(self._prefill_buckets)} exceeds the "
                f"context cap {self._ctx_cap} (max_seq_len / KV pool)")
        self._queue = AdmissionQueue(
            queue_bound or _env.serving_queue_bound(),
            on_expire=lambda r: _C_REQS.labels(outcome="expired").inc())
        self._active: list = []
        self._exec: dict = {}
        # per-executable FLOPs from compile-time cost_analysis (same
        # key space as _exec; None = unavailable — accounting just
        # skips, the MFU gauge stays absent rather than wrong)
        self._exec_flops: dict = {}
        self._n_chips = 1
        # per-request span traces (serving/tracing.py): explicit kwarg
        # > MXNET_TRACE_REQUESTS (default on).  The store keeps the
        # slowest N + every error/evicted trace; /v1/requests serves it
        from .tracing import TraceStore

        self._trace_enabled = bool(
            trace_requests if trace_requests is not None
            else _env.trace_requests())
        self._traces = TraceStore()
        self._lock = threading.Lock()          # guards _exec + counters
        self._stop_evt = threading.Event()     # close() requested
        self._drain = True                     # finish in-flight on stop
        self._drained = False                  # loop ran its final drain
        self._thread = None
        self._warm = False
        self._joined_seq = 0
        self._latencies: deque = deque(maxlen=2048)
        self._ttfts: deque = deque(maxlen=2048)
        self._tok_window: deque = deque(maxlen=64)   # (t, n_generated)
        self._mounted: list = []
        # fallback sampling-key chain for submitters with an UNSEEDED
        # mx.random stream: that state is thread-local, so two fresh
        # HTTP worker threads would otherwise both start at PRNGKey(0)
        # and draw IDENTICAL keys for concurrent requests
        import secrets

        from jax import random as _jr

        self._master_key = _jr.PRNGKey(secrets.randbits(31))

    # -- bucket grids ------------------------------------------------------
    def _make_page_buckets(self):
        cap = pages_for(self._ctx_cap, self._page_size)
        out, b = [], 1
        while b < cap:
            out.append(b)
            b *= 2
        out.append(cap)
        return out

    def manifest(self):
        """The AOT signature manifest: every executable the server
        compiles at startup, with its operand avals and the
        dtype/AMP-epoch keying — the serving half of the deployment-IR
        boundary (the block half is ``serving.export_artifact``)."""
        V, ps = self._cfg.vocab_size, self._page_size
        sigs = []
        for L in self._prefill_buckets:
            P = bucket_for(pages_for(L, ps), self._page_buckets)
            sigs.append({"phase": "prefill", "tokens": L, "pages": P,
                         "inputs": [[1, L, "int32"]]})
        for B in self._batch_buckets:
            for P in self._page_buckets:
                sigs.append({"phase": "decode", "batch": B, "pages": P,
                             "context": P * ps})
            sigs.append({"phase": "sample", "batch": B})
        return {
            "model": self._name,
            "param_dtype": self._kv.dtype,
            "page_size": ps,
            "kv_pages": self._kv.pages,
            "context_cap": self._ctx_cap,
            "batch_buckets": self._batch_buckets,
            "prefill_buckets": self._prefill_buckets,
            "page_buckets": self._page_buckets,
            "signatures": sigs,
        }

    # -- executable bodies (pure; traced once each at AOT time) ------------
    def _prefill_body(self, L, P):
        import jax.numpy as jnp

        from ..gluon.model_zoo.language.llama import prefill_apply

        cfg, ps = self._cfg, self._page_size

        def fn(params, kp, vp, ids, n, table):
            # ids (1, L) right-padded prompt; n = true length; table (1, P)
            logits, ks, vs = prefill_apply(params, cfg, ids)
            j = jnp.arange(L)
            pids = jnp.where(j < n, table[0, j // ps], 0)  # pads -> scratch
            offs = j % ps
            kn = ks[:, 0].transpose(2, 0, 1, 3)      # (L, layers, Hkv, hd)
            vn = vs[:, 0].transpose(2, 0, 1, 3)
            kp = kp.at[:, pids, :, offs, :].set(kn.astype(kp.dtype))
            vp = vp.at[:, pids, :, offs, :].set(vn.astype(vp.dtype))
            return logits[0, n - 1], kp, vp

        return fn

    def _decode_body(self, B, P):
        import jax.numpy as jnp

        from ..gluon.model_zoo.language.llama import decode_apply

        cfg, ps = self._cfg, self._page_size
        Hkv, hd = cfg.num_kv_heads, cfg.head_dim

        def fn(params, kp, vp, ids, pos, table):
            # ids/pos (B,); table (B, P); padded rows point at scratch
            rows = jnp.arange(B)
            pids = table[rows, pos // ps]
            offs = pos % ps
            pools = {"k": kp, "v": vp}

            def kv_join(layer, k_new, v_new):
                kn = k_new[:, :, 0, :]               # (B, Hkv, hd)
                vn = v_new[:, :, 0, :]
                pools["k"] = pools["k"].at[layer, pids, :, offs, :].set(
                    kn.astype(pools["k"].dtype))
                pools["v"] = pools["v"].at[layer, pids, :, offs, :].set(
                    vn.astype(pools["v"].dtype))
                K = pools["k"][layer][table].transpose(0, 2, 1, 3, 4) \
                    .reshape(B, Hkv, P * ps, hd)
                V = pools["v"][layer][table].transpose(0, 2, 1, 3, 4) \
                    .reshape(B, Hkv, P * ps, hd)
                return K, V, pos + 1

            logits = decode_apply(params, cfg, ids, pos, kv_join)
            return logits, pools["k"], pools["v"]

        return fn

    @staticmethod
    def _sample_body(B):
        import jax
        import jax.numpy as jnp

        def fn(logits, keys, steps, temps):
            # greedy rows: pure argmax.  temperature rows: categorical
            # under fold_in(request key, draw index) — sampling is a
            # pure function of the request, NOT of batch composition,
            # so continuous batching / eviction cannot change a
            # sampled sequence
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def draw(lg, k, s, t):
                kk = jax.random.fold_in(k, s)
                return jax.random.categorical(
                    kk, lg / jnp.where(t > 0, t, 1.0))

            drawn = jax.vmap(draw)(logits.astype(jnp.float32), keys,
                                   steps, temps).astype(jnp.int32)
            return jnp.where(temps > 0, drawn, greedy)

        return fn

    # -- AOT compilation (the ONLY place jax tracing happens) --------------
    def _sig_key(self, phase, *dyn_avals):
        # dispatch-cache keying: avals + AMP epoch + ctx kind, so an AMP
        # flip or context move after warmup misses (and recompiles with
        # an attributed cause) instead of serving a stale executable
        return _dc.signature_key(f"serving:{self._name}", dyn_avals,
                                 extra=(phase,))

    def _avals(self, phase, **dims):
        import jax
        import numpy as np

        ps = self._page_size
        if phase == "prefill":
            L, P = dims["L"], dims["P"]
            return (jax.ShapeDtypeStruct((1, L), np.int32),
                    jax.ShapeDtypeStruct((), np.int32),
                    jax.ShapeDtypeStruct((1, P), np.int32))
        if phase == "decode":
            B, P = dims["B"], dims["P"]
            return (jax.ShapeDtypeStruct((B,), np.int32),
                    jax.ShapeDtypeStruct((B,), np.int32),
                    jax.ShapeDtypeStruct((B, P), np.int32))
        B = dims["B"]
        return (jax.ShapeDtypeStruct((B, self._cfg.vocab_size),
                                     np.dtype(self._kv.dtype)),
                jax.ShapeDtypeStruct((B, 2), np.uint32),
                jax.ShapeDtypeStruct((B,), np.int32),
                jax.ShapeDtypeStruct((B,), np.float32))

    def _aot_compile(self, phase, cause, **dims):
        """Lower + compile one signature and cache it under its key.
        ``cause`` is ``aot_warmup`` at startup; a steady-state call that
        lands here is a ``steady_state_miss`` — the smoke and bench
        assert there are none after warmup."""
        import jax

        t0 = time.perf_counter()
        dyn = self._avals(phase, **dims)
        key = self._sig_key(phase, *dyn)
        with self._lock:
            if key in self._exec:
                return self._exec[key]
        if self._plan is not None:
            # planner-sharded AOT: params carry their NamedSharding from
            # the placement at construction; pools and dynamic operands
            # replicate over the same mesh (every executable input must
            # live on one device set)
            rep = self._rep_sharding
            param_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                                   sharding=v.sharding)
                           for k, v in self._params.items()}
            pool_aval = jax.ShapeDtypeStruct(self._kv.k_pool.shape,
                                             self._kv.k_pool.dtype,
                                             sharding=rep)
            dyn = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype,
                                             sharding=rep) for a in dyn)
        else:
            param_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                           for k, v in self._params.items()}
            pool_aval = jax.ShapeDtypeStruct(self._kv.k_pool.shape,
                                             self._kv.k_pool.dtype)
        # planner path: pin every output replicated — with a tp plan the
        # lm_head leaves logits vocab-sharded, and the sample executable
        # (plus the host-side token fetch) expects the full row; the
        # all-gather GSPMD inserts here is exactly tensor-parallel
        # serving's logits gather before sampling
        jit_kw = {} if self._plan is None else \
            {"out_shardings": self._rep_sharding}
        # warm-start path: a persisted executable for this exact
        # signature (avals + plan digest + jax fingerprint) skips the
        # trace AND the XLA compile — no compile event is recorded
        # because no trace happened (the cache-hit counter carries the
        # observability; the PR 3 zero-fresh-trace assertions rely on
        # exactly this)
        ckey = None
        if self._cc is not None:
            # cfg fields ride the key: two configs with identical param
            # shapes (rope_base, rms_eps, ...) compile DIFFERENT math
            cfg_fp = tuple(sorted(
                (k, repr(v)) for k, v in vars(self._cfg).items()))
            ckey = self._cc.key(
                f"serving:{self._name}:{phase}",
                (repr(key), cfg_fp,
                 _ccache.aval_signature(param_avals),
                 _ccache.aval_signature(pool_aval)),
                plan_digest=self._plan.digest()
                if self._plan is not None else None)
            cached, cmeta = self._cc.load_executable_entry(ckey)
            if cached is not None:
                # warm load: the FLOP count rides the cache entry, so
                # online MFU accounting stays fed with no compile to ask
                with self._lock:
                    self._exec[key] = cached
                    self._exec_flops[key] = cmeta.get("flops")
                return cached
        if phase == "prefill":
            jit_fn = jax.jit(self._prefill_body(dims["L"], dims["P"]),
                             donate_argnums=(1, 2), **jit_kw)
            aot_args = (param_avals, pool_aval, pool_aval) + tuple(dyn)
        elif phase == "decode":
            jit_fn = jax.jit(self._decode_body(dims["B"], dims["P"]),
                             donate_argnums=(1, 2), **jit_kw)
            aot_args = (param_avals, pool_aval, pool_aval) + tuple(dyn)
        else:
            jit_fn = jax.jit(self._sample_body(dims["B"]), **jit_kw)
            aot_args = tuple(dyn)
        compiled = jit_fn.lower(*aot_args).compile()
        # per-executable FLOPs, captured ONCE while the compiled object
        # is in hand (layer 1 of the introspection plane): steady-state
        # dispatch then accounts a known constant — no cost re-derive,
        # no host sync
        flops = _introspection.flops_of(compiled)
        with self._lock:
            self._exec[key] = compiled
            self._exec_flops[key] = flops
        label = ":".join([self._name, phase] +
                         [f"{k}{v}" for k, v in sorted(dims.items())])
        _telemetry.compile_event("serving", label,
                                 time.perf_counter() - t0, cause)
        if ckey is not None:
            self._cc.store_executable(
                ckey, jit_fn, *aot_args,
                meta={"flops": flops} if flops else None)
        return compiled

    def _aot_warmup(self):
        """Compile the full manifest grid.  Every steady-state signature
        the scheduler can produce is covered: prompt lengths pad to a
        prefill bucket, batch sizes to a batch bucket, page counts to a
        page bucket."""
        t0 = time.perf_counter()
        ps = self._page_size
        for L in self._prefill_buckets:
            P = bucket_for(pages_for(L, ps), self._page_buckets)
            self._aot_compile("prefill", "aot_warmup", L=L, P=P)
        for B in self._batch_buckets:
            for P in self._page_buckets:
                self._aot_compile("decode", "aot_warmup", B=B, P=P)
            self._aot_compile("sample", "aot_warmup", B=B)
        if 1 not in self._batch_buckets:
            self._aot_compile("sample", "aot_warmup", B=1)
        self._warm = True
        return time.perf_counter() - t0

    def _lookup_exec(self, phase, **dims):
        """``(compiled, flops)`` for one signature; flops is the
        compile-time cost_analysis count (None = unavailable)."""
        key = self._sig_key(phase, *self._avals(phase, **dims))
        with self._lock:
            compiled = self._exec.get(key)
        if compiled is None:
            # a post-warmup miss is a contract violation the tracer makes
            # visible (cause steady_state_miss) — but the request is
            # served, not dropped
            compiled = self._aot_compile(phase, "steady_state_miss",
                                         **dims)
        with self._lock:
            flops = self._exec_flops.get(key)
        return compiled, flops

    # -- replica handoff ---------------------------------------------------
    @classmethod
    def join_replica(cls, net, donor, **kw):
        """Replica scale-out handoff: build a new engine whose frozen
        params are DONATED by a running ``donor`` engine through the
        live-resharding transfer (donor plan → this engine's ``plan``
        kw, replicated when absent) instead of re-read from the net or
        loaded from disk.  The donor keeps serving throughout — its
        param arrays are immutable and the transfer only reads them.
        The join-to-first-token clock
        (``mxnet_serving_join_to_first_token_seconds``) starts at the
        handoff and stops at this replica's first generated token."""
        return cls(net, params_from=donor, **kw)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """AOT-compile the manifest and start the engine loop thread."""
        if self._thread is not None:
            return self
        import jax

        self._n_chips = max(1, jax.device_count())
        if self._plan is not None:
            # the executables expect every operand on the plan's mesh:
            # replicate the KV pools once up front (they stay replicated
            # through the donate round trip, so this is one-time work)
            self._kv.k_pool = jax.device_put(self._kv.k_pool,
                                             self._rep_sharding)
            self._kv.v_pool = jax.device_put(self._kv.v_pool,
                                             self._rep_sharding)
        self._aot_warmup()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="mxnet-serving-engine",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self, drain=True, timeout=60):
        """Stop the loop: with ``drain`` in-flight sequences finish and
        queued requests get a clean shutdown error; without, everything
        resolves with the shutdown error immediately."""
        self._drain = bool(drain)
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise MXNetError(
                    f"serving engine loop did not stop within {timeout}s "
                    "(drain still in progress — call close() again or "
                    "close(drain=False) to abort in-flight work)")
            self._thread = None
        self.unmount_http()

    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def join(self, timeout=None):
        """Block until the loop thread exits (SIGTERM drain path)."""
        t = self._thread
        if t is not None:
            t.join(timeout)

    # -- request surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, temperature=0.0,
               eos_id=None, deadline_ms=None, trace_id=None):
        """Enqueue a generation request; returns the Request future.
        Raises QueueFullError at the admission bound and MXNetError
        when the server is shutting down or the prompt cannot fit.

        ``trace_id`` stitches cross-process traces: a fleet router
        stamps its own (numeric) trace id into the replica request so
        the replica-side spans land in the SAME tree the router's
        queue_wait/dispatch spans live in."""
        if self._stop_evt.is_set():
            raise MXNetError("serving engine is shutting down")
        if not self._warm:
            raise MXNetError("serving engine not started — call start()")
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      temperature=temperature, eos_id=eos_id,
                      deadline_ms=deadline_ms if deadline_ms is not None
                      else (self._deadline_ms or None))
        if self._trace_enabled:
            from .tracing import RequestTrace

            req.trace = RequestTrace(
                int(trace_id) if trace_id is not None else req.id)
            req.trace.event("submitted", prompt_len=int(req.prompt.size),
                            max_new_tokens=req.max_new_tokens)
            req.on_resolve = self._trace_finished
        if req.temperature > 0:
            req.key = self._request_key()
        L = int(req.prompt.size)
        if bucket_for(L, self._prefill_buckets) is None:
            raise MXNetError(
                f"prompt length {L} exceeds the largest prefill bucket "
                f"{max(self._prefill_buckets)}")
        if pages_for(L, self._page_size) > self._kv.pages - 1:
            raise MXNetError(
                f"prompt length {L} can never fit the KV pool "
                f"({self._kv.pages - 1} allocatable pages)")
        self._queue.put(req)
        _G_QUEUE.set(len(self._queue))
        if self._drained:
            # raced past the stop check while the loop ran its FINAL
            # queue drain: nobody will ever pop this request — reject it
            # now instead of leaving the future to time out
            self._queue.drain(lambda r: MXNetError(
                f"request {r.id} rejected: server shutting down"))
            raise MXNetError("serving engine is shutting down")
        return req

    def _request_key(self):
        """Per-request sampling key.  A submitter whose thread seeded
        mx.random gets the next key of that stream (reproducible under
        mx.random.seed, the documented contract); an unseeded thread
        falls back to the engine's own split chain so concurrent
        requests from fresh threads never share a key."""
        from .. import random as _rnd

        if _rnd._S.key is not None:
            # mxtpu: noqa[MXT010] submit-time 8-byte key fetch, off-loop
            return _np.asarray(_rnd._next_key(), dtype=_np.uint32)
        from jax import random as _jr

        with self._lock:
            self._master_key, sub = _jr.split(self._master_key)
        # mxtpu: noqa[MXT010] submit-time 8-byte key fetch, off-loop
        return _np.asarray(sub, dtype=_np.uint32)

    # -- the steady-state loop (NO tracing allowed in here: MXT050) --------
    def _run_loop(self):
        from .. import lifecycle

        consec_fail = 0
        while True:
            if lifecycle.stop_requested():
                self._stop_evt.set()
            if self._stop_evt.is_set():
                if not self._drain:
                    self._abort_active()
                if not self._active:
                    break
            try:
                did_work = self._step()
                consec_fail = 0
            except Exception as e:
                # an engine step must never kill the loop thread: the
                # chaos seams (serving.decode_step) raise BEFORE any
                # KV/sequence mutation, so the step simply retries —
                # and a real bug becomes a counted, logged failure
                # instead of a silently dead server.  Bounded, not
                # blind: each failure backs off (no hot spin), the log
                # is rate-limited, and a PERSISTENT failure resolves
                # the wedged in-flight work with the error instead of
                # hanging its callers forever
                _C_STEP_FAIL.inc()
                consec_fail += 1
                if consec_fail <= 3 or consec_fail % 10 == 0:
                    _LOGGER.warning(
                        "serving engine step failed (%r); retrying "
                        "(%d consecutive)", e, consec_fail)
                if consec_fail >= self._MAX_CONSEC_STEP_FAILURES:
                    _LOGGER.critical(
                        "serving engine step failed %d times in a row "
                        "(%r); failing the wedged in-flight work so "
                        "callers unblock", consec_fail, e)
                    # a persistently broken serving step is an abnormal
                    # event: dump the ring (host-side file IO only) so
                    # the post-mortem shows what preceded the wedge
                    from .. import flight_recorder as _flight

                    _flight.record_event(
                        "lifecycle", event="serving_step_failure",
                        consecutive=consec_fail, error=repr(e)[:200])
                    _flight.dump_blackbox("serving_step_failure")
                    self._fail_active(e)
                    consec_fail = 0
                self._stop_evt.wait(0.05)
                did_work = False
                continue
            if not did_work and not self._stop_evt.is_set():
                self._queue.wait_nonempty(0.02)
        # flag BEFORE the final drain: a submit() that races past the
        # stop check either lands before this drain (drained here) or
        # observes the flag and self-drains — never stranded
        self._drained = True
        n = self._queue.drain(lambda r: MXNetError(
            f"request {r.id} rejected: server shutting down"))
        for _ in range(n):
            # distinct from the in-flight work the drain COMPLETED
            # (those finish with their normal outcome): these never ran.
            # Fleet-level retry accounting keys on this — a
            # drain_rejected completion is safe to resubmit elsewhere
            _C_REQS.labels(outcome="drain_rejected").inc()
        self._publish_gauges()

    def _step(self):
        did = False
        while (not self._stop_evt.is_set()
               and len(self._active) < self._max_batch):
            req = self._queue.pop_ready()
            if req is None:
                break
            with self._timed("prefill"):
                admitted = self._admit(req)
            did = True
            if not admitted:
                break    # pool full even after eviction: stop admitting
        if self._active:
            with self._timed("decode"):
                self._decode_step()
            did = True
        self._publish_gauges()
        return did

    class _Timed:
        __slots__ = ("name", "t0")

        def __init__(self, name):
            self.name = name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            _H_PHASE.labels(phase=self.name).observe(
                time.perf_counter() - self.t0)
            return False

    def _timed(self, name):
        return self._Timed(name)

    def _publish_gauges(self):
        _G_QUEUE.set(len(self._queue))
        _G_ACTIVE.set(len(self._active))
        _G_PAGES.labels(state="free").set(self._kv.pages_free)
        _G_PAGES.labels(state="used").set(self._kv.pages_used)
        win = self._tok_window
        if len(win) >= 2:
            dt = win[-1][0] - win[0][0]
            toks = sum(n for _, n in list(win)[1:])
            if dt > 0:
                _G_TOKS_S.set(toks / dt)
                _G_TOKS_CHIP.set(toks / dt / self._n_chips)

    def _admit(self, req):
        """Prefill one request (or its post-eviction continuation).
        Returns False when the pool cannot host it right now (request
        requeued)."""
        import jax.numpy as jnp

        tr = req.trace
        try:
            # chaos seam: a tripped admission loses nothing — the
            # request returns to the queue FRONT and the next loop
            # iteration retries it
            _fault.check("serving.admit")
        except Exception as e:
            _LOGGER.warning("serving.admit fault for request %s (%r); "
                            "requeued", req.id, e)
            if tr is not None:
                tr.event("requeued", reason="admit_fault")
                tr.last_enqueue_t = time.perf_counter()
            self._queue.requeue(req)
            return False
        if req.expired():
            if tr is not None:
                tr.event("deadline_expired", where="prefill")
            req.resolve(DeadlineExceededError(
                f"request {req.id} expired before prefill"))
            _C_REQS.labels(outcome="expired").inc()
            return True
        ids_full = req.full_ids()
        L = int(ids_full.size)
        if L >= self._ctx_cap or \
                bucket_for(L, self._prefill_buckets) is None:
            # an evicted continuation can outgrow the prefill grid even
            # though the original prompt fit — finish with what we have
            # rather than erroring a half-served request
            if req.tokens:
                self._finish(req, "length")
            else:
                req.resolve(MXNetError(
                    f"request {req.id}: prompt length {L} exceeds the "
                    f"serving context cap {self._ctx_cap}"))
                _C_REQS.labels(outcome="rejected").inc()
            return True
        # admission NEVER evicts: preempting an active sequence to start
        # a new one would let two sequences that cannot coexist in the
        # pool ping-pong each other (one token per full prefill).  New
        # work waits for free pages; eviction is reserved for GROWTH of
        # already-running sequences (_decode_step).
        if not self._kv.alloc(req.id, L):
            if tr is not None:
                tr.event("requeued", reason="pool_full")
                tr.last_enqueue_t = time.perf_counter()
            self._queue.requeue(req)
            return False
        Lb = bucket_for(L, self._prefill_buckets)
        P = bucket_for(pages_for(L, self._page_size), self._page_buckets)
        # close the queue span BEFORE the executable lookup: a
        # steady-state miss compiles for seconds, and that time must
        # read as a compile, never as queue congestion
        t_q_end = time.perf_counter()
        if tr is not None:
            tr.add_span("queue_wait", tr.last_enqueue_t, t_q_end,
                        prefills=req.prefills)
        compiled, flops = self._lookup_exec("prefill", L=Lb, P=P)
        t_pre = time.perf_counter()
        if tr is not None and t_pre - t_q_end > 1e-3:
            tr.add_span("compile_wait", t_q_end, t_pre, bucket=Lb)
        ids = jnp.asarray(_np.concatenate(
            [ids_full, _np.zeros(Lb - L, dtype=_np.int32)])[None, :])
        table = jnp.asarray(
            self._kv.table_rows([req.id], P), dtype=jnp.int32)
        last_logits, kp, vp = compiled(
            self._params, self._kv.k_pool, self._kv.v_pool, ids,
            _np.int32(L), table)
        self._kv.k_pool, self._kv.v_pool = kp, vp
        if flops:
            _introspection.account_flops(flops, kind="serving_prefill")
        req.prefills += 1
        if req.prefills == 1:
            _C_TOKENS.labels(kind="prompt").inc(L)
        t_sm = time.perf_counter()
        pid = tr.add_span("prefill", t_pre, t_sm, tokens=L, bucket=Lb) \
            if tr is not None else 0
        tok = self._sample([last_logits], [req])[0]
        if tr is not None:
            # the host-side clock: prefill dispatch is async, the
            # sample's fused token fetch is where the wall time lands
            tr.add_span("sample", t_sm, time.perf_counter(), parent=pid)
        if req.first_token_t is None:
            req.first_token_t = time.monotonic()
            _H_TTFT.observe(req.first_token_t - req.submitted)
            with self._lock:
                self._ttfts.append(req.first_token_t - req.submitted)
            if self._join_t0 is not None:
                # replica handoff acceptance metric: donated-params
                # join -> this replica's FIRST served token
                _H_JOIN.observe(req.first_token_t - self._join_t0)
                self._join_t0 = None
        req.tokens.append(tok)
        _C_TOKENS.labels(kind="generated").inc()
        if self._is_finished(req, tok, L):
            self._kv.free(req.id)
            self._finish(req, "stop" if tok == req.eos_id else "length")
            return True
        self._joined_seq += 1
        self._active.append(_Seq(req, L, tok, self._joined_seq))
        return True

    def _evictable(self, seq):
        """A sequence may be evicted only if its continuation (prompt +
        generated so far) can re-prefill later — evicting one that has
        outgrown the prefill grid would silently truncate it."""
        n = int(seq.req.full_ids().size)
        return n < self._ctx_cap and \
            bucket_for(n, self._prefill_buckets) is not None

    def _youngest_evictable(self, exclude=None):
        for seq in reversed(self._active):
            if seq is not exclude and self._evictable(seq):
                return seq
        return None

    def _evict(self, seq):
        """Return a sequence's pages and requeue its continuation (the
        prompt plus everything generated so far re-prefills later)."""
        self._active.remove(seq)
        self._kv.free(seq.req.id)
        tr = seq.req.trace
        if tr is not None:
            tr.event("evicted", cache_len=seq.cache_len,
                     generated=len(seq.req.tokens))
            tr.last_enqueue_t = time.perf_counter()
        self._queue.requeue(seq.req)
        _C_EVICT.inc()

    def _decode_step(self):
        import jax.numpy as jnp

        # chaos seam, checked BEFORE any KV/table/sequence mutation: a
        # trip unwinds to the loop guard with zero torn state and the
        # step retries next iteration
        _fault.check("serving.decode_step")
        # grow tables first; eviction inside can shrink the active set
        for seq in list(self._active):
            if seq not in self._active:
                continue
            while not self._kv.ensure(seq.req.id, seq.cache_len + 1):
                victim = self._youngest_evictable(exclude=seq)
                if victim is not None:
                    self._evict(victim)
                    continue
                if self._evictable(seq):
                    # nothing else to evict: hand this one back to the
                    # queue (its pages free the pool for smaller work)
                    self._evict(seq)
                else:
                    # unrestorable AND the pool is exhausted: finish at
                    # the current length rather than wedging the loop
                    self._active.remove(seq)
                    self._kv.free(seq.req.id)
                    self._finish(seq.req, "length")
                break
        if not self._active:
            return
        B = len(self._active)
        Bb = bucket_for(B, self._batch_buckets)
        max_pages = max(pages_for(s.cache_len + 1, self._page_size)
                        for s in self._active)
        P = bucket_for(max_pages, self._page_buckets)
        compiled, flops = self._lookup_exec("decode", B=Bb, P=P)
        pad = Bb - B
        sids = [s.req.id for s in self._active] + [None] * pad
        ids = jnp.asarray([s.last_token for s in self._active] + [0] * pad,
                          dtype=jnp.int32)
        pos = jnp.asarray([s.cache_len for s in self._active] + [0] * pad,
                          dtype=jnp.int32)
        table = jnp.asarray(self._kv.table_rows(sids, P), dtype=jnp.int32)
        t_dec = time.perf_counter()
        logits, kp, vp = compiled(self._params, self._kv.k_pool,
                                  self._kv.v_pool, ids, pos, table)
        self._kv.k_pool, self._kv.v_pool = kp, vp
        if flops:
            _introspection.account_flops(flops, kind="serving_decode")
        _H_OCCUPANCY.observe(B / Bb)
        rows = list(self._active)
        t_sm = time.perf_counter()
        toks = self._sample(logits, [s.req for s in rows], batched=True)
        t_done = time.perf_counter()
        now = time.monotonic()
        n_new = 0
        for seq, tok in zip(rows, toks):
            req = seq.req
            tr = req.trace
            if tr is not None:
                # per-decode-step residency: this request rode THIS
                # batched step (host-side stamps; the sample child is
                # where the one fused token fetch lands)
                did = tr.add_span("decode_step", t_dec, t_sm,
                                  step=len(req.tokens), batch=B,
                                  bucket=Bb)
                tr.add_span("sample", t_sm, t_done, parent=did)
            seq.cache_len += 1
            seq.last_token = tok
            req.tokens.append(tok)
            n_new += 1
            if self._is_finished(req, tok, seq.cache_len + 1):
                self._active.remove(seq)
                self._kv.free(req.id)
                self._finish(req, "stop" if tok == req.eos_id
                             else "length")
        _C_TOKENS.labels(kind="generated").inc(n_new)
        self._tok_window.append((now, n_new))

    def _sample(self, logits, reqs, batched=False):
        """Sample one token per row; returns python ints.  THE one host
        sync per engine step lives here (everything upstream stays
        lazily dispatched)."""
        import jax.numpy as jnp

        if batched:
            lg = logits
            B = lg.shape[0]
        else:
            lg = jnp.stack(logits)
            B = len(logits)
        pad = B - len(reqs)
        zero_key = _np.zeros(2, dtype=_np.uint32)
        temps = [r.temperature for r in reqs] + [0.0] * pad
        keys = [r.key if r.key is not None else zero_key
                for r in reqs] + [zero_key] * pad
        steps = [len(r.tokens) for r in reqs] + [0] * pad
        compiled, flops = self._lookup_exec("sample", B=B)
        toks = compiled(lg, jnp.asarray(_np.stack(keys)),
                        jnp.asarray(steps, dtype=jnp.int32),
                        jnp.asarray(temps, dtype=jnp.float32))
        if flops:
            _introspection.account_flops(flops, kind="serving_sample")
        # mxtpu: noqa[MXT010] ONE fused token fetch per engine step IS the design (has_overflow precedent)
        host = _np.asarray(toks)
        return [int(t) for t in host[:len(reqs)]]

    def _trace_finished(self, req):
        """Request.resolve hook: classify the outcome, file the trace
        in the tail-retention store, and merge its spans into the
        Chrome trace when the profiler is active.  Every resolution
        path (finish, queue/prefill deadline, shutdown drain, step
        failure) flows through resolve(), so this one hook sees them
        all — host-side work only."""
        tr = req.trace
        if tr is None:
            return
        err = req.error
        if err is None:
            outcome = req.finish_reason or "done"
        elif isinstance(err, DeadlineExceededError):
            outcome = "expired"
        else:
            outcome = "error"
        tr.finish(outcome, error=err)
        self._traces.add(tr)
        tr.emit_chrome()

    def _is_finished(self, req, tok, ctx_next):
        return (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)
                or ctx_next >= self._ctx_cap)

    def _finish(self, req, reason):
        req.finish_reason = reason
        req.resolve()
        with self._lock:
            self._latencies.append(req.finished_t - req.submitted)
        _H_LATENCY.observe(req.finished_t - req.submitted)
        # outcome distinguishes how a request ENDED: "stop" (hit its
        # eos_id) vs "length" (max_new_tokens or the context/pool cap —
        # the signal an operator watches for silent truncation)
        _C_REQS.labels(outcome=reason).inc()

    # consecutive step failures before the loop stops retrying and
    # fails the in-flight work (at the 0.05s per-failure backoff this
    # is ~2.5s of a persistently broken step — far beyond any armed
    # chaos burst, far short of a caller's request timeout)
    _MAX_CONSEC_STEP_FAILURES = 50

    def _fail_active(self, error):
        """Resolve every in-flight sequence with ``error`` (persistent
        step failure): their pages free, their callers unblock with the
        real cause, and the loop keeps serving whatever work does not
        hit the broken path."""
        for seq in list(self._active):
            self._kv.free(seq.req.id)
            seq.req.resolve(MXNetError(
                f"request {seq.req.id} failed: serving engine step "
                f"persistently failing ({error!r})"))
            _C_REQS.labels(outcome="error").inc()
        self._active = []

    def _abort_active(self):
        for seq in list(self._active):
            self._kv.free(seq.req.id)
            seq.req.resolve(MXNetError(
                f"request {seq.req.id} aborted: server closed without "
                "drain"))
            _C_REQS.labels(outcome="aborted").inc()
        self._active = []

    # -- observability -----------------------------------------------------
    def stats(self):
        """JSON-able engine snapshot (served at /v1/serving)."""
        with self._lock:
            # snapshot under the lock: the loop thread appends to the
            # deque and iterating a mutating deque raises
            lat = sorted(self._latencies)
            ttft = sorted(self._ttfts)

        def _pct_of(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else None

        def pct(p):
            return _pct_of(lat, p)

        with self._lock:
            n_exec = len(self._exec)
        return {
            "model": self._name,
            "queue_depth": len(self._queue),
            "active_sequences": len(self._active),
            "kv_pages": {"free": self._kv.pages_free,
                         "used": self._kv.pages_used,
                         "page_size": self._page_size,
                         "pool_bytes": self._kv.nbytes()},
            "compiled_signatures": n_exec,
            "warm": self._warm,
            "latency_s": {"p50": pct(0.50), "p99": pct(0.99),
                          "count": len(lat)},
            # the fleet router's health monitor feeds on these (queue
            # depth above + TTFT percentiles here) to score replicas
            "ttft_s": {"p50": _pct_of(ttft, 0.50),
                       "p99": _pct_of(ttft, 0.99), "count": len(ttft)},
            "tokens_per_s": _G_TOKS_S.value,
            "tokens_per_s_per_chip": _G_TOKS_CHIP.value,
            "context_cap": self._ctx_cap,
            "buckets": {"batch": self._batch_buckets,
                        "prefill": self._prefill_buckets,
                        "pages": self._page_buckets},
            "request_traces": {"enabled": self._trace_enabled,
                               "traced": self._traces.count()},
        }

    # -- HTTP plane (mounted beside /metrics on the telemetry server) ------
    def mount_http(self, prefix="/v1"):
        """Register ``{prefix}/completions`` (POST), ``{prefix}/serving``
        (GET), and the ``{prefix}/requests`` trace-debug route (GET:
        the tail-retained per-request span trees) on the telemetry HTTP
        endpoint."""
        comp, stat = prefix + "/completions", prefix + "/serving"
        reqs = prefix + "/requests"
        _telemetry.register_http_route(comp, self._http_completions)
        _telemetry.register_http_route(stat, self._http_stats)
        _telemetry.register_http_route(reqs, self._http_requests)
        self._mounted = [comp, stat, reqs]
        return self

    def unmount_http(self):
        for path in self._mounted:
            _telemetry.unregister_http_route(path)
        self._mounted = []

    def _http_stats(self, method, path, query, body):
        return 200, "application/json", json.dumps(self.stats()).encode()

    def _http_requests(self, method, path, query, body):
        doc = self._traces.snapshot()
        doc["enabled"] = self._trace_enabled
        return 200, "application/json", json.dumps(doc).encode()

    def _http_completions(self, method, path, query, body):
        from .scheduler import QueueFullError

        if method != "POST":
            return 405, "application/json", b'{"error": "POST only"}'
        try:
            data = json.loads(body or b"{}")
            prompt = data["prompt"]
        except (ValueError, KeyError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"bad request: {e!r}"}).encode()
        try:
            req = self.submit(
                prompt,
                max_new_tokens=int(data.get("max_new_tokens", 16)),
                temperature=float(data.get("temperature", 0.0)),
                eos_id=data.get("eos_id"),
                deadline_ms=data.get("deadline_ms"),
                trace_id=data.get("trace_id"))
        except QueueFullError as e:
            _C_REQS.labels(outcome="rejected").inc()
            return 429, "application/json", json.dumps(
                {"error": str(e)}).encode()
        except MXNetError as e:
            return 400, "application/json", json.dumps(
                {"error": str(e)}).encode()
        try:
            res = req.result(timeout=float(data.get("timeout_s", 120)))
        except DeadlineExceededError as e:
            return 408, "application/json", json.dumps(
                {"error": str(e)}).encode()
        except MXNetError as e:
            return 503, "application/json", json.dumps(
                {"error": str(e)}).encode()
        if data.get("return_trace") and req.trace is not None:
            # cross-process span handoff: the caller (fleet router)
            # grafts this replica-side tree into its own trace so
            # /v1/requests stays end-to-end across the router hop
            res["trace"] = req.trace.to_dict()
        return 200, "application/json", json.dumps(res).encode()


def serve(net, port=None, install_signals=True, on_ready=None,
          **engine_kw):
    """Blocking server entrypoint: start the telemetry HTTP endpoint
    (serving routes mounted beside ``/metrics``), run the engine until a
    graceful stop (SIGTERM/SIGINT or ``lifecycle.request_stop``), drain,
    and return the lifecycle exit code (``EXIT_PREEMPTED`` after a stop
    request, 0 after ``close()``).

    ``on_ready(engine, bound_port)`` fires once the engine is warm and
    the routes are mounted (embedders, smoke tests).  The caller owns
    ``sys.exit(serve(...))``."""
    from .. import lifecycle

    if install_signals:
        lifecycle.install_signal_handlers()
    server = _telemetry.start_http_server(
        port if port is not None else (_env.serving_port() or 0))
    engine = ServingEngine(net, **engine_kw)
    engine.start()
    engine.mount_http()
    bound = server.server_address[1]
    print(f"mxnet_tpu serving: engine up on 127.0.0.1:{bound} "
          f"(/v1/completions, /v1/serving, /metrics)", flush=True)
    if on_ready is not None:
        on_ready(engine, bound)
    try:
        engine.join()
    finally:
        engine.close()
    if lifecycle.stop_requested():
        lifecycle.cancel_grace_deadline()
        return lifecycle.EXIT_PREEMPTED
    return 0
