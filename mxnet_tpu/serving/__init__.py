"""mxnet_tpu.serving — AOT-lowered inference with continuous batching.

The production serving plane the ROADMAP's north star calls for, built
from four cooperating layers (ISSUE 8):

- :mod:`.artifact` — the Relay/TVM-style deployment-IR boundary:
  ``HybridBlock.export`` freezes symbol + params + a signature manifest
  with StableHLO; :func:`load_artifact` reconstructs and AOT-warms it.
- :mod:`.scheduler` — requests, the bounded admission queue with
  deadlines, and bucket arithmetic for continuous batching.
- :mod:`.kvcache` — the block-paged KV pool (page tables per sequence,
  scratch page 0 for padded rows, eviction by returning pages).
- :mod:`.engine` — :class:`ServingEngine`: AOT-compiled prefill /
  paged-decode / sampling executables keyed with the PR 1 dispatch-cache
  discipline, a zero-fresh-trace steady-state loop, telemetry metric
  families, the HTTP inference routes mounted beside ``/metrics``, and
  :func:`serve` honoring the PR 5 graceful-drain lifecycle.

Quickstart::

    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.gluon.model_zoo.language.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    net(mx.nd.zeros((1, 8), dtype="int32"))
    eng = serving.ServingEngine(net).start()
    req = eng.submit([1, 2, 3], max_new_tokens=8)
    print(req.result(timeout=30)["token_ids"])
    eng.close()
"""
from .artifact import (LoadedArtifact, export_artifact, load_artifact,
                       manifest_path, write_manifest)
from .engine import ServingEngine, serve
from .kvcache import PagedKVCache, pages_for
from .scheduler import (AdmissionQueue, DeadlineExceededError,
                        QueueFullError, Request, bucket_for, parse_buckets)

__all__ = [
    "ServingEngine", "serve",
    "export_artifact", "load_artifact", "write_manifest", "manifest_path",
    "LoadedArtifact",
    "PagedKVCache", "pages_for",
    "Request", "AdmissionQueue", "QueueFullError", "DeadlineExceededError",
    "bucket_for", "parse_buckets",
]
