"""Frozen deployment artifacts: the Relay/TVM-style IR boundary.

``HybridBlock.export`` historically wrote only the legacy deploy pair
(``path-symbol.json`` + ``path-{epoch:04d}.params``).  For serving, the
same call now also freezes an **artifact manifest**
(``path-artifact.json``): the input signatures (avals), the AMP epoch
and parameter dtype the trace was taken under, and the lowered
**StableHLO** text per signature — parameters ride as arguments, not
constants, so the IR is architecture-sized, not weight-sized.  The
manifest is the contract between export time and serve time: a server
AOT-compiles every manifest signature at startup and then never traces
again (the zero-fresh-trace guarantee the PR 3 compile tracer audits).

``load_artifact`` is the reverse direction: it reconstructs the block
from the symbol + params files via ``SymbolBlock.imports``, hybridizes
it, and (by default) warms every manifest signature so first-request
latency pays no trace.  Round trip is exact: the loaded block produces
identical outputs to the live exporting block (tests pin this for both
formats).
"""
from __future__ import annotations

import json
import os

import numpy as _np

from ..base import MXNetError

__all__ = ["export_artifact", "load_artifact", "write_manifest",
           "manifest_path", "LoadedArtifact"]

MANIFEST_FORMAT = "mxtpu-serving-artifact"
MANIFEST_VERSION = 1


def manifest_path(path):
    return path + "-artifact.json"


def _sig_entry(inputs):
    out = []
    for a in inputs:
        out.append({"shape": [int(s) for s in a.shape],
                    "dtype": str(_np.dtype(a.dtype))})
    return out


def _input_avals(sig):
    import jax

    return [jax.ShapeDtypeStruct(tuple(e["shape"]), _np.dtype(e["dtype"]))
            for e in sig["inputs"]]


def _lower_stablehlo(block, sig_avals):
    """Lower the block's pure functional form at one signature to
    StableHLO text.  Parameters and the RNG key are arguments (the IR
    freezes the *computation*, weights live in the params file)."""
    import jax

    from ..parallel.functional import functionalize

    apply_fn, params = functionalize(block, train_mode=False)
    param_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params.items()}
    key_aval = jax.ShapeDtypeStruct((2,), _np.uint32)
    lowered = jax.jit(apply_fn).lower(param_avals, key_aval, *sig_avals)
    try:
        return lowered.as_text(dialect="stablehlo")
    except TypeError:        # older jax: no dialect kwarg (default IS mlir)
        return lowered.as_text()


def write_manifest(block, path, epoch=0, signatures=None, include_ir=True):
    """Write ``path-artifact.json`` for an exported block.

    ``signatures``: list of input tuples (arrays or ShapeDtypeStructs);
    defaults to the block's last traced signature.  Lowering failures
    are recorded per signature (``lower_error``) instead of failing the
    export — the symbol+params round trip stays intact either way."""
    import jax

    sigs = signatures if signatures is not None else \
        [getattr(block, "_last_input_shapes", None)]
    if not sigs or sigs[0] is None:
        raise MXNetError("write_manifest needs at least one input "
                         "signature (run a forward or pass signatures=)")
    from ..ndarray.ndarray import _AMP

    n_inputs = len(sigs[0])
    input_names = ["data"] if n_inputs == 1 else \
        [f"data{i}" for i in range(n_inputs)]
    entries = []
    for sig in sigs:
        entry = {"inputs": _sig_entry(sig)}
        if include_ir:
            try:
                avals = [jax.ShapeDtypeStruct(tuple(a.shape),
                                              _np.dtype(a.dtype))
                         for a in sig]
                entry["stablehlo"] = _lower_stablehlo(block, avals)
            except Exception as e:   # IR is advisory; round trip is not
                entry["lower_error"] = repr(e)[:500]
        entries.append(entry)
    params = sorted(block._collect_params_with_prefix())
    dtypes = sorted({str(p.data().dtype) for p in
                     block._collect_params_with_prefix().values()})
    manifest = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "symbol": os.path.basename(path) + "-symbol.json",
        "params": os.path.basename(path) + f"-{epoch:04d}.params",
        "epoch": int(epoch),
        "input_names": input_names,
        "signatures": entries,
        "amp_epoch": _AMP["epoch"] if _AMP["on"] else None,
        "param_dtypes": dtypes,
        "num_params": len(params),
    }
    with open(manifest_path(path), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def export_artifact(block, path, epoch=0, signatures=None,
                    include_ir=True):
    """Export a hybridized block as a frozen artifact: the legacy deploy
    pair (via ``HybridBlock.export``) plus the manifest covering every
    signature in ``signatures`` (default: the last traced one).  Returns
    the manifest dict."""
    example = signatures[0] if signatures else ()
    # manifest=False: export would lower signature 0 for a one-entry
    # manifest we immediately replace — skip the duplicate work
    block.export(path, epoch, *example, manifest=False)
    return write_manifest(block, path, epoch=epoch, signatures=signatures,
                          include_ir=include_ir)


class LoadedArtifact:
    """A reconstructed frozen block plus its AOT executable table.

    ``block`` is the ``SymbolBlock`` rebuilt from symbol + params (kept
    for training-time escape hatches: autograd, fine-tuning).  Serving
    calls do NOT go through it — :meth:`warmup` lowers the evaluated
    graph to one ``jax.jit`` executable **per manifest signature**
    (keyed with the PR 1 ``dispatch_cache.signature_key`` discipline,
    compile events recorded under kind ``serving``), and ``__call__``
    dispatches to the compiled table.  A call at a non-manifest
    signature still works but compiles with cause ``steady_state_miss``
    — the tracer makes the contract violation visible instead of
    silently retracing."""

    def __init__(self, block, manifest, path, plan=None):
        self.block = block
        self.manifest = manifest
        self.path = path
        self.warmed = 0
        self._exec: dict = {}
        # rng key rides as a (fixed) argument: inference-mode graphs
        # draw nothing, and freezing the aval keeps signatures stable
        self._zero_key = _np.zeros(2, dtype=_np.uint32)
        names = block._input_names + block._sym_param_names
        self._param_vals = [block.params.get(n).data()._get()
                            for n in block._sym_param_names]
        # planner-sharded AOT (tensor-parallel serving): place the
        # frozen params per the plan once; every signature then compiles
        # against the sharded avals (zero-fresh-trace contract intact)
        self._plan = plan
        self._rep_sharding = None
        if plan is not None:
            import jax

            mesh = plan.build_mesh()
            self._rep_sharding = plan.replicated(mesh)
            self._param_vals = [
                jax.device_put(v, plan.sharding(n, mesh))
                for n, v in zip(block._sym_param_names,
                                self._param_vals)]
        heads = block._sym._heads

        from ..symbol.symbol import evaluate

        def pure(key_val, *vals):
            feed = dict(zip(names, vals))
            outs, _ = evaluate(heads, feed, rng_key=key_val,
                               training=False, collect_state=False)
            return tuple(outs) if len(outs) != 1 else outs[0]

        self._pure = pure

    def signatures(self):
        return [_input_avals(s) for s in self.manifest["signatures"]]

    def _sig_key(self, avals):
        from ..ndarray import dispatch_cache as _dc

        return _dc.signature_key(f"serving:artifact:{self.path}", avals)

    def _aot_compile_signature(self, avals, cause):
        import jax
        import time

        t0 = time.perf_counter()
        key = self._sig_key(avals)
        if key in self._exec:
            return self._exec[key]
        rep = self._rep_sharding
        key_aval = jax.ShapeDtypeStruct((2,), _np.uint32, sharding=rep) \
            if rep is not None else jax.ShapeDtypeStruct((2,), _np.uint32)
        p_avals = [jax.ShapeDtypeStruct(v.shape, v.dtype,
                                        sharding=getattr(v, "sharding",
                                                         None))
                   if rep is not None else
                   jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for v in self._param_vals]
        in_avals = [jax.ShapeDtypeStruct(tuple(a.shape),
                                         _np.dtype(a.dtype),
                                         sharding=rep)
                    if rep is not None else
                    jax.ShapeDtypeStruct(tuple(a.shape),
                                         _np.dtype(a.dtype))
                    for a in avals]
        compiled = jax.jit(self._pure).lower(
            key_aval, *in_avals, *p_avals).compile()
        self._exec[key] = compiled
        from .. import telemetry as _telemetry

        _telemetry.compile_event(
            "serving", f"artifact:{os.path.basename(self.path)}",
            time.perf_counter() - t0, cause)
        self.warmed += 1
        return compiled

    def warmup(self):
        """AOT-compile every manifest signature; returns how many fresh
        executables this built."""
        before = self.warmed
        for avals in self.signatures():
            self._aot_compile_signature(avals, "aot_warmup")
        return self.warmed - before

    def __call__(self, *args):
        from ..context import current_context
        from ..ndarray.ndarray import NDArray

        vals = [a._get() if isinstance(a, NDArray) else a for a in args]
        key = self._sig_key(vals)
        compiled = self._exec.get(key)
        if compiled is None:
            compiled = self._aot_compile_signature(vals,
                                                   "steady_state_miss")
        if self._rep_sharding is not None:
            # the sharded executable needs every operand on the plan's
            # mesh; committed single-device NDArrays do not auto-reshard
            import jax

            vals = [jax.device_put(v, self._rep_sharding) for v in vals]
        out = compiled(self._zero_key, *vals, *self._param_vals)
        ctx = args[0].context if args and isinstance(args[0], NDArray) \
            else current_context()
        if isinstance(out, tuple):
            return tuple(NDArray._from_jax(v, ctx) for v in out)
        return NDArray._from_jax(out, ctx)


def load_artifact(path, ctx=None, warm=True, plan=None):
    """Load an exported artifact back: manifest + symbol + params ->
    hybridized SymbolBlock, AOT-warmed across the manifest signatures
    (``warm=False`` skips the warmup).  Outputs are identical to the
    exporting block's.  ``plan``: a
    :class:`~mxnet_tpu.parallel.planner.ShardingPlan` — params are
    placed per the plan's PartitionSpecs and every signature
    AOT-compiles sharded (tensor-parallel serving)."""
    mpath = manifest_path(path)
    if not os.path.exists(mpath):
        raise MXNetError(
            f"no artifact manifest at {mpath} — re-export with this "
            "build (legacy -symbol.json exports predate the manifest)")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("format") != MANIFEST_FORMAT:
        raise MXNetError(f"{mpath}: not a {MANIFEST_FORMAT} manifest")
    base = os.path.dirname(path)
    sym_file = os.path.join(base, manifest["symbol"])
    params_file = os.path.join(base, manifest["params"])
    from ..gluon.block import SymbolBlock

    block = SymbolBlock.imports(sym_file, manifest["input_names"],
                                params_file, ctx)
    block.hybridize()
    art = LoadedArtifact(block, manifest, path, plan=plan)
    if warm:
        art.warmup()
    return art
