"""Per-request serving traces: a span tree per request with tail-based
retention.

Layer 2 of the runtime introspection plane (ISSUE 14).  Aggregate
serving metrics (p50/p99 histograms, tokens/s gauges) answer "is the
fleet healthy"; they cannot answer "*which* request blew p99 and where
its time went".  This module records, per request, the full residency
chain — **queue wait → prefill → per-decode-step → sample → finish** —
plus the discrete events that explain anomalies (eviction, requeue,
deadline expiry), as a nestable span tree.

Recording contract (the MXT010/MXT050 hot-path discipline): every
operation here is a host-side ``perf_counter`` read plus a list append
under no lock (a trace is only ever written by one thread at a time —
the submitter before admission, the engine loop after).  No device
arrays, no host syncs, no traces; ``MXNET_TRACE_REQUESTS=0`` removes
even the appends.

Retention is **tail-based**: a bounded ring of recent traces would keep
exactly the requests nobody asks about and evict the outliers.  The
:class:`TraceStore` therefore always keeps

- the ``MXNET_TRACE_KEEP_SLOWEST`` slowest completed requests,
- every error / evicted / deadline-expired request (bounded ring), and
- a recent-completions ring (context for diffing an outlier against
  its healthy neighbors).

Export: the engine serves ``store.snapshot()`` at ``/v1/requests``
beside ``/metrics``, and a finished trace's spans merge into the Chrome
trace through ``profiler._record_span`` (category
``serving_request``) whenever the profiler is active.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque

from .. import env as _env

__all__ = ["RequestTrace", "TraceStore"]

# spans/events per trace are bounded: a 32k-token generation (or a
# request requeue-churning behind a full pool for minutes) must not
# grow an unbounded list — past the cap, entries count, not accumulate
_MAX_SPANS = 1024
_MAX_EVENTS = 256


class RequestTrace:
    """One request's span tree + event list.

    Spans are ``[span_id, name, t0, t1, parent_id, attrs]`` on the
    perf_counter clock (the profiler's clock, so Chrome export aligns);
    ``span_id`` 0 is the implicit root covering submit → finish.
    Writers: exactly one thread at any moment (submitter, then the
    engine loop), so appends need no lock."""

    __slots__ = ("trace_id", "t0", "wall0", "spans", "events", "outcome",
                 "t_end", "dropped_spans", "dropped_events", "evicted",
                 "error", "last_enqueue_t", "_next_id")

    def __init__(self, trace_id):
        self.trace_id = trace_id
        self.t0 = time.perf_counter()
        # the engine bumps this on every (re-)enqueue so each
        # queue_wait span measures ITS wait, not time since submit
        self.last_enqueue_t = self.t0
        self.wall0 = time.time()
        self.spans: list = []
        self.events: list = []
        self.outcome = None
        self.t_end = None
        self.dropped_spans = 0
        self.dropped_events = 0
        self.evicted = False
        self.error = None
        self._next_id = itertools.count(1)

    # -- recording ---------------------------------------------------------
    def add_span(self, name, t0, t1, parent=0, **attrs):
        """Record a completed span; returns its id (parent for
        children).  Past the per-trace cap spans are counted, not
        kept — the tree stays bounded for any generation length."""
        if len(self.spans) >= _MAX_SPANS:
            self.dropped_spans += 1
            return 0
        sid = next(self._next_id)
        self.spans.append([sid, str(name), float(t0), float(t1),
                           int(parent), attrs or None])
        return sid

    def event(self, name, **attrs):
        """Record an instant event (eviction, requeue, deadline...).
        Bounded like spans — but the retention-relevant flags still
        update past the cap."""
        if name == "evicted":
            self.evicted = True
        if len(self.events) >= _MAX_EVENTS:
            self.dropped_events += 1
            return
        self.events.append([time.perf_counter(), str(name),
                            attrs or None])

    def finish(self, outcome, error=None):
        """Close the root span (idempotent — first outcome wins)."""
        if self.t_end is not None:
            return
        self.t_end = time.perf_counter()
        self.outcome = str(outcome)
        self.error = error

    # -- views -------------------------------------------------------------
    @property
    def duration_s(self):
        return ((self.t_end if self.t_end is not None
                 else time.perf_counter()) - self.t0)

    def to_dict(self):
        """JSON-able nested span tree (children under their parents,
        times relative to submit in seconds)."""
        nodes = {0: {"name": "request", "t0": 0.0,
                     "dur_s": round(self.duration_s, 6), "children": []}}
        for sid, name, t0, t1, parent, attrs in self.spans:
            node = {"name": name, "t0": round(t0 - self.t0, 6),
                    "dur_s": round(t1 - t0, 6), "children": []}
            if attrs:
                node["attrs"] = attrs
            nodes[sid] = node
        for sid, name, t0, t1, parent, attrs in self.spans:
            nodes.get(parent, nodes[0])["children"].append(nodes[sid])
        return {
            "trace_id": self.trace_id,
            "time": self.wall0,
            "outcome": self.outcome,
            "error": repr(self.error) if self.error is not None else None,
            "duration_s": round(self.duration_s, 6),
            "evicted": self.evicted,
            "dropped_spans": self.dropped_spans,
            "dropped_events": self.dropped_events,
            "events": [{"t": round(t - self.t0, 6), "name": n,
                        **({"attrs": a} if a else {})}
                       for t, n, a in self.events],
            "tree": nodes[0],
        }

    def emit_chrome(self):
        """Merge this trace's spans into the profiler's Chrome trace
        (no-op unless the profiler is active).  Each request gets its
        own tid row so concurrent requests do not interleave."""
        try:
            from .. import profiler as _prof
        except Exception:   # pragma: no cover - import cycle safety
            return
        tid = 2000 + (self.trace_id % 997)
        _prof._record_span(f"req{self.trace_id}", self.t0,
                           self.t_end or time.perf_counter(),
                           cat="serving_request", tid=tid,
                           args={"trace_id": self.trace_id,
                                 "outcome": self.outcome})
        for sid, name, t0, t1, parent, attrs in self.spans:
            _prof._record_span(f"req{self.trace_id}:{name}", t0, t1,
                               cat="serving_request", tid=tid,
                               args=attrs)


class TraceStore:
    """Completed-trace retention with a tail bias.

    Three overlapping buckets (deduped by trace id at export):

    - ``slowest`` — min-heap of the N slowest completed traces
      (``keep_slowest``, default ``MXNET_TRACE_KEEP_SLOWEST``): the
      p99 outlier is ALWAYS here, no matter how much healthy traffic
      followed it.
    - ``errors`` — every error / evicted / expired trace (bounded
      ring: anomalies are rare, but a misbehaving client must not
      evict the history of a real incident).
    - ``recent`` — plain ring of latest completions (the healthy
      baseline an outlier is diffed against)."""

    def __init__(self, keep_slowest=None, keep_recent=64,
                 keep_errors=64):
        self._n_slow = int(keep_slowest if keep_slowest is not None
                           else _env.trace_keep_slowest())
        self._slow: list = []            # min-heap of (dur, seq, trace)
        self._seq = itertools.count()
        self._recent: deque = deque(maxlen=int(keep_recent))
        self._errors: deque = deque(maxlen=int(keep_errors))
        self._lock = threading.Lock()
        self._added = 0

    def add(self, trace):
        """File one finished trace (engine loop / submitter thread)."""
        with self._lock:
            self._added += 1
            self._recent.append(trace)
            if trace.error is not None or trace.evicted:
                self._errors.append(trace)
            item = (trace.duration_s, next(self._seq), trace)
            if len(self._slow) < self._n_slow:
                heapq.heappush(self._slow, item)
            elif item[0] > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)

    def count(self):
        """Total traces ever filed (cheap — stats()/dashboards poll
        this; the full span-tree dump is :meth:`snapshot`)."""
        with self._lock:
            return self._added

    def traces(self):
        """Retained traces, deduped, slowest-first, each tagged with
        the retention buckets that kept it."""
        with self._lock:
            tagged = {}
            for dur, _, tr in self._slow:
                tagged.setdefault(id(tr), [tr, set()])[1].add("slowest")
            for tr in self._errors:
                tagged.setdefault(id(tr), [tr, set()])[1].add("errors")
            for tr in self._recent:
                tagged.setdefault(id(tr), [tr, set()])[1].add("recent")
        out = [(tr, sorted(tags)) for tr, tags in tagged.values()]
        out.sort(key=lambda p: -p[0].duration_s)
        return out

    def snapshot(self):
        """JSON-able store dump (the ``/v1/requests`` payload)."""
        items = []
        for tr, tags in self.traces():
            d = tr.to_dict()
            d["retained_by"] = tags
            items.append(d)
        with self._lock:
            added = self._added
        return {
            "traced_requests": added,
            "retention": {"keep_slowest": self._n_slow,
                          "recent_ring": self._recent.maxlen,
                          "error_ring": self._errors.maxlen},
            "requests": items,
        }

    def clear(self):
        with self._lock:
            self._slow = []
            self._recent.clear()
            self._errors.clear()
            self._added = 0
