"""Block-paged KV-cache pool for the serving engine.

The dense per-sequence cache (``LlamaForCausalLM.init_decode_cache``)
reserves ``max_seq_len`` slots per request — at serving batch sizes that
wastes HBM proportional to the *longest possible* context times the
batch.  The paged pool (the vLLM observation, applied to this repo's
decode math) slices the cache into fixed-size pages and gives every
sequence a page table instead: HBM held is proportional to tokens
*actually cached*, sequences join/leave the decode batch without
copying, and eviction is "return the pages".

Device layout (one pool per engine, shared by every sequence):

    k_pool, v_pool : (num_layers, pages, num_kv_heads, page_size, head_dim)

Page ``0`` is a reserved scratch page that is never allocated: the
engine routes writes of padded batch rows and padded prompt positions
there, so the jitted executables never branch on row validity — garbage
lands in scratch, and gathers of real rows see only their own pages
(positions past a row's length are masked with the flash-attention
``NEG_INF`` convention, whose softmax weight is exactly 0.0).

Host-side state (page tables, the free list) is plain Python guarded by
one lock — it is touched a handful of times per *step*, never per
token, and only by the engine loop thread plus close().
"""
from __future__ import annotations

import threading

from ..base import MXNetError

__all__ = ["PagedKVCache", "pages_for"]


def pages_for(n_tokens, page_size):
    """Pages needed to hold ``n_tokens`` (at least one — a sequence owns
    a page from admission so its first decode step has somewhere to
    write)."""
    return max(1, -(-int(n_tokens) // int(page_size)))


class PagedKVCache:
    """Page allocator + device pools.  The engine owns the jitted
    scatter/gather; this class owns *which page belongs to whom*."""

    def __init__(self, num_layers, num_kv_heads, head_dim, pages,
                 page_size, dtype="float32"):
        import jax.numpy as jnp

        if pages < 2:
            raise MXNetError("PagedKVCache needs >= 2 pages (page 0 is "
                             "the reserved scratch page)")
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.pages = int(pages)
        self.page_size = int(page_size)
        self.dtype = dtype
        shape = (self.num_layers, self.pages, self.num_kv_heads,
                 self.page_size, self.head_dim)
        self.k_pool = jnp.zeros(shape, dtype=dtype)
        self.v_pool = jnp.zeros(shape, dtype=dtype)
        self._lock = threading.Lock()
        self._free = list(range(self.pages - 1, 0, -1))  # pop() -> page 1 first
        self._tables: dict = {}                          # seq_id -> [page,...]

    # -- capacity ----------------------------------------------------------
    @property
    def pages_free(self):
        with self._lock:
            return len(self._free)

    @property
    def pages_used(self):
        with self._lock:
            return (self.pages - 1) - len(self._free)

    def nbytes(self):
        """Device bytes held by both pools."""
        return int(self.k_pool.nbytes) + int(self.v_pool.nbytes)

    # -- allocation --------------------------------------------------------
    def alloc(self, seq_id, n_tokens):
        """Give ``seq_id`` a table covering ``n_tokens``.  Returns True on
        success; False when the pool cannot cover it (caller evicts or
        defers admission — never partially allocates)."""
        need = pages_for(n_tokens, self.page_size)
        with self._lock:
            if seq_id in self._tables:
                raise MXNetError(f"seq {seq_id!r} already allocated")
            if need > len(self._free):
                return False
            self._tables[seq_id] = [self._free.pop() for _ in range(need)]
            return True

    def ensure(self, seq_id, n_tokens):
        """Grow ``seq_id``'s table to cover ``n_tokens`` (no-op when it
        already does).  Returns False — table untouched — when the pool
        is out of pages."""
        need = pages_for(n_tokens, self.page_size)
        with self._lock:
            table = self._tables[seq_id]
            grow = need - len(table)
            if grow <= 0:
                return True
            if grow > len(self._free):
                return False
            table.extend(self._free.pop() for _ in range(grow))
            return True

    def free(self, seq_id):
        """Return ``seq_id``'s pages to the pool (idempotent).  Returns
        the number of pages released."""
        with self._lock:
            table = self._tables.pop(seq_id, None)
            if not table:
                return 0
            self._free.extend(table)
            return len(table)

    def table(self, seq_id):
        with self._lock:
            return list(self._tables[seq_id])

    def holds(self, seq_id):
        with self._lock:
            return seq_id in self._tables

    def table_rows(self, seq_ids, n_pages):
        """Page tables for ``seq_ids`` as row lists padded to ``n_pages``
        with the scratch page; ids of None (padded batch rows) get an
        all-scratch row.  The engine turns this into the (B, P) int32
        device operand of the decode executable."""
        rows = []
        with self._lock:
            for sid in seq_ids:
                table = self._tables.get(sid, ()) if sid is not None else ()
                if len(table) > n_pages:
                    raise MXNetError(
                        f"seq {sid!r} holds {len(table)} pages > page "
                        f"bucket {n_pages}")
                rows.append(list(table) + [0] * (n_pages - len(table)))
        return rows
