"""Continuous-batching scheduler: requests, the bounded admission queue,
and bucket arithmetic.

The serving engine runs one loop over two interleaved phases — prefill
(admit a waiting request: run its prompt through the full-context
forward, seed its KV pages) and decode (one token for every *active*
sequence as a single batched executable call).  Sequences join the
decode batch the step after their prefill and leave the step they
finish; the batch is padded up to a *bucket* size so the step always
hits a pre-compiled executable (the AOT manifest), never a fresh trace.

This module is the host-side half: request objects with completion
events, the bounded FIFO with deadline expiry, and the pure bucket
helpers.  Nothing here touches jax.
"""
from __future__ import annotations

import itertools
import threading
import time

import numpy as _np

from ..base import MXNetError

__all__ = ["Request", "QueueFullError", "DeadlineExceededError",
           "AdmissionQueue", "bucket_for", "parse_buckets"]


class QueueFullError(MXNetError):
    """Admission queue at its bound — the clean backpressure signal
    (HTTP 429 on the wire).  Raised at submit time, never later."""


class DeadlineExceededError(MXNetError):
    """The request's deadline expired before it produced a result."""


def parse_buckets(spec, what="bucket"):
    """``"1,2,4,8"`` -> sorted unique positive ints."""
    try:
        vals = sorted({int(tok) for tok in str(spec).split(",") if
                       tok.strip()})
    except ValueError:
        raise MXNetError(f"bad {what} spec {spec!r}: comma-separated "
                         "positive integers expected") from None
    if not vals or vals[0] <= 0:
        raise MXNetError(f"bad {what} spec {spec!r}: positive integers "
                         "expected")
    return vals


def bucket_for(n, buckets):
    """Smallest bucket >= n, or None when n exceeds every bucket (the
    caller rejects — padding DOWN would truncate)."""
    for b in buckets:
        if n <= b:
            return b
    return None


_REQ_IDS = itertools.count(1)


class Request:
    """One generation request and its completion future.

    ``prompt`` is a 1-D int32 array of token ids.  ``temperature`` 0 =
    greedy argmax; > 0 samples via the keyed categorical.  ``deadline``
    (monotonic seconds, absolute) bounds *queueing + generation*: an
    expired request resolves with :class:`DeadlineExceededError` instead
    of silently serving stale work.  The engine fills ``tokens``
    (generated ids only) and resolves ``_done``; callers block in
    :meth:`result`."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature", "eos_id",
                 "deadline", "submitted", "first_token_t", "finished_t",
                 "tokens", "error", "_done", "prefills", "key",
                 "finish_reason", "trace", "on_resolve")

    def __init__(self, prompt, max_new_tokens=16, temperature=0.0,
                 eos_id=None, deadline_ms=None):
        self.id = next(_REQ_IDS)
        prompt = _np.asarray(prompt, dtype=_np.int32).reshape(-1)
        if prompt.size == 0:
            raise MXNetError("empty prompt")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens <= 0:
            raise MXNetError("max_new_tokens must be positive")
        self.temperature = float(temperature)
        self.eos_id = eos_id
        now = time.monotonic()
        self.submitted = now
        self.deadline = now + deadline_ms / 1e3 if deadline_ms else None
        self.first_token_t = None
        self.finished_t = None
        self.tokens: list = []
        self.error = None
        self._done = threading.Event()
        self.prefills = 0     # > 1 = the sequence was evicted + re-prefilled
        # sampling key, captured from mx.random's keyed state at submit
        # time (on the caller's thread).  Draw i is fold_in(key, i) — a
        # pure function of (request, draw index), so sampled sequences
        # are independent of batch composition, eviction, and peer
        # traffic, and reproducible under mx.random.seed.
        self.key = None
        self.finish_reason = None   # "stop" (eos) | "length" (caps)
        # per-request introspection (serving/tracing.py): the engine
        # attaches a RequestTrace at submit when MXNET_TRACE_REQUESTS
        # is on, and an on_resolve hook that files the finished trace —
        # every resolution path (finish, deadline, eviction-drain,
        # shutdown, step failure) flows through resolve(), so one hook
        # covers them all
        self.trace = None
        self.on_resolve = None

    def full_ids(self):
        """Prompt plus everything generated so far — the prefill input
        of a post-eviction continuation."""
        if not self.tokens:
            return self.prompt
        return _np.concatenate(
            [self.prompt, _np.asarray(self.tokens, dtype=_np.int32)])

    # -- engine side -------------------------------------------------------
    def resolve(self, error=None):
        self.error = error
        self.finished_t = time.monotonic()
        hook = self.on_resolve
        if hook is not None:
            try:
                hook(self)
            except Exception:   # tracing must never fail a request
                pass
        self._done.set()

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline

    # -- caller side -------------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block for the completion dict (raises the request's error)."""
        if not self._done.wait(timeout):
            raise MXNetError(f"request {self.id}: no result within "
                             f"{timeout}s")
        if self.error is not None:
            raise self.error
        ttft = (self.first_token_t - self.submitted) \
            if self.first_token_t else None
        return {
            "request_id": self.id,
            "prompt_len": int(self.prompt.size),
            "token_ids": list(self.tokens),
            "finish_reason": self.finish_reason,
            "ttft_s": ttft,
            "latency_s": self.finished_t - self.submitted,
            "prefills": self.prefills,
        }


class AdmissionQueue:
    """Bounded FIFO with deadline expiry.

    ``put`` raises :class:`QueueFullError` at the bound — backpressure
    belongs at admission, where the caller can still route elsewhere,
    not deep in the engine.  ``requeue`` (eviction re-admission) is
    exempt from the bound: the engine already accepted that work and
    dropping it would turn a capacity wobble into a lost request.
    ``on_expire(req)`` fires for every request whose deadline lapses
    in the queue (the engine counts these in its outcome metrics)."""

    def __init__(self, bound, on_expire=None):
        self._bound = int(bound)
        self._on_expire = on_expire
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: list = []

    def __len__(self):
        with self._lock:
            return len(self._items)

    def put(self, req):
        with self._lock:
            if len(self._items) >= self._bound:
                raise QueueFullError(
                    f"serving queue full ({self._bound} waiting); retry "
                    "with backoff or raise MXNET_SERVING_QUEUE")
            self._items.append(req)
            self._cond.notify()

    def requeue(self, req):
        """Put an evicted sequence's request back at the FRONT (it keeps
        its age-order priority; bound exempt, see class docstring)."""
        with self._lock:
            self._items.insert(0, req)
            self._cond.notify()

    def pop_ready(self):
        """Next request that has not expired (expired ones are resolved
        with DeadlineExceededError and skipped).  None when empty."""
        now = time.monotonic()
        with self._lock:
            while self._items:
                req = self._items.pop(0)
                if req.expired(now):
                    if req.trace is not None:
                        req.trace.event("deadline_expired",
                                        where="queue")
                    req.resolve(DeadlineExceededError(
                        f"request {req.id} expired after "
                        f"{now - req.submitted:.3f}s in queue"))
                    if self._on_expire is not None:
                        self._on_expire(req)
                    continue
                return req
            return None

    def wait_nonempty(self, timeout):
        """Block until an item is (probably) available or timeout."""
        with self._lock:
            if self._items:
                return True
            return self._cond.wait(timeout)

    def drain(self, error_factory):
        """Resolve every waiting request with ``error_factory(req)`` —
        the shutdown path: queued work is rejected cleanly, in-flight
        work (already out of the queue) finishes."""
        with self._lock:
            items, self._items = self._items, []
        for req in items:
            req.resolve(error_factory(req))
        return len(items)
