"""Per-replica health: the HEALTHY → SUSPECT → EJECTED → PROBING
state machine and the monitor thread that drives it.

The router never *asks* a replica whether it is healthy at dispatch
time — that would put a network round trip on the request path.
Instead each replica carries a :class:`ReplicaHealth` record updated
from three signal sources, and dispatch just reads it:

- **dispatch outcomes** — every router→replica send reports
  success/failure here; ``eject_threshold`` consecutive failures trip
  the circuit breaker (EJECTED);
- **heartbeats** — the :class:`HealthMonitor` polls each replica's
  stats through the ``router.health_probe`` seam every
  ``MXNET_FLEET_PROBE_INTERVAL_MS``; a probe failure counts like a
  dispatch failure, and the carried queue-depth/TTFT-p99 gauges mark
  an overloaded-but-alive replica SUSPECT (deprioritized, not ejected);
- **liveness** — a SIGKILLed replica process fails its next probe
  *and* its ``alive()`` check, so death detection is bounded by one
  probe interval (default 250 ms — well under the 1 s budget).

Re-admission is half-open: after a cooldown (doubling per consecutive
ejection, full recovery resets it) the replica moves to PROBING, where
at most ``probe_budget`` live requests may be in flight at once
(:meth:`ReplicaHealth.try_acquire_probe`).  ``probe_successes``
consecutive wins restore HEALTHY; any failure re-ejects with a longer
cooldown.  The bounded budget is the point — a half-open replica must
prove itself on a trickle, not absorb a thundering herd and fall over
again.

No jax imports here (the router does zero device work); the clock is
injectable so the unit tests drive transitions deterministically.
"""
from __future__ import annotations

import logging
import threading
import time

__all__ = ["HEALTHY", "SUSPECT", "EJECTED", "PROBING", "ReplicaHealth",
           "HealthMonitor"]

HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"
PROBING = "probing"

_LOGGER = logging.getLogger(__name__)


class ReplicaHealth:
    """One replica's health record (thread-safe; all signal sources —
    dispatcher threads, the monitor, the hedge path — write here)."""

    def __init__(self, eject_threshold=3, cooldown_s=0.5,
                 max_cooldown_s=30.0, probe_budget=2, probe_successes=2,
                 clock=time.monotonic):
        self.eject_threshold = max(1, int(eject_threshold))
        self.base_cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        self.probe_budget = max(1, int(probe_budget))
        self.probe_successes = max(1, int(probe_successes))
        self._clock = clock
        self._lock = threading.Lock()
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.ejections = 0            # consecutive (resets on recovery)
        self.ejected_at = None
        self.last_heartbeat = clock()
        self.queue_depth = None
        self.ttft_p99 = None
        self._probe_ok = 0
        self._probe_inflight = 0
        self.transitions: list = []   # (t, from, to, reason) ring

    # -- transitions -------------------------------------------------------
    def _move(self, to, reason):
        if self.state == to:
            return
        self.transitions.append((self._clock(), self.state, to, reason))
        del self.transitions[:-64]
        _LOGGER.info("replica health: %s -> %s (%s)", self.state, to,
                     reason)
        self.state = to

    def note_success(self):
        """A dispatch or probe completed: SUSPECT recovers, PROBING
        counts toward the half-open quota."""
        with self._lock:
            self.consecutive_failures = 0
            if self.state == SUSPECT:
                self._move(HEALTHY, "success")
            elif self.state == PROBING:
                self._probe_ok += 1
                if self._probe_ok >= self.probe_successes:
                    self.ejections = 0
                    self._move(HEALTHY, f"{self._probe_ok} probe wins")

    def note_failure(self, reason="dispatch"):
        """A dispatch or probe failed.  Failure streaks trip the
        breaker; ANY failure while half-open re-ejects (with a doubled
        cooldown — the replica just proved it is not ready)."""
        with self._lock:
            self.consecutive_failures += 1
            if self.state == PROBING:
                self._eject(f"probe failure ({reason})")
            elif self.state in (HEALTHY, SUSPECT):
                if self.consecutive_failures >= self.eject_threshold:
                    self._eject(f"{self.consecutive_failures} consecutive "
                                f"failures ({reason})")
                else:
                    self._move(SUSPECT, reason)

    def note_suspect(self, reason):
        """Soft signal (overload gauges): deprioritize without counting
        toward ejection — the replica is alive, just slow."""
        with self._lock:
            if self.state == HEALTHY:
                self._move(SUSPECT, reason)

    def _eject(self, reason):
        # caller holds the lock
        self.ejections += 1
        self.ejected_at = self._clock()
        self._probe_ok = 0
        self._probe_inflight = 0
        self._move(EJECTED, reason)

    def note_heartbeat(self, queue_depth=None, ttft_p99=None):
        """A successful probe's payload: liveness stamp + load gauges
        (the monitor feeds these from /v1/serving stats)."""
        with self._lock:
            self.last_heartbeat = self._clock()
            if queue_depth is not None:
                self.queue_depth = int(queue_depth)
            if ttft_p99 is not None:
                self.ttft_p99 = float(ttft_p99)

    def cooldown_s(self):
        """Current ejection cooldown: doubles per consecutive ejection
        up to the cap (the same growth shape as the fault.py backoff,
        deterministic here — probes are already bounded traffic)."""
        n = max(0, self.ejections - 1)
        return min(self.base_cooldown_s * (2 ** n), self.max_cooldown_s)

    def tick(self):
        """Clock-driven transition: EJECTED → PROBING once the cooldown
        elapses.  Called by the monitor each probe interval."""
        with self._lock:
            if self.state == EJECTED and self.ejected_at is not None \
                    and self._clock() - self.ejected_at >= \
                    self.cooldown_s():
                self._probe_ok = 0
                self._probe_inflight = 0
                self._move(PROBING, f"cooldown {self.cooldown_s():.2f}s "
                           "elapsed")

    # -- dispatch gating ---------------------------------------------------
    def dispatchable(self):
        """May the router send this replica live traffic right now?
        HEALTHY/SUSPECT: yes.  PROBING: only within the probe budget
        (the caller must pair with try_acquire_probe).  EJECTED: no."""
        with self._lock:
            return self.state in (HEALTHY, SUSPECT) or (
                self.state == PROBING
                and self._probe_inflight < self.probe_budget)

    def try_acquire_probe(self):
        """Claim one slot of half-open probe traffic (True = granted).
        HEALTHY/SUSPECT replicas grant unconditionally — only PROBING
        meters."""
        with self._lock:
            if self.state in (HEALTHY, SUSPECT):
                return True
            if self.state == PROBING and \
                    self._probe_inflight < self.probe_budget:
                self._probe_inflight += 1
                return True
            return False

    def release_probe(self):
        with self._lock:
            if self._probe_inflight > 0:
                self._probe_inflight -= 1

    def snapshot(self):
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "ejections": self.ejections,
                "cooldown_s": self.cooldown_s(),
                "queue_depth": self.queue_depth,
                "ttft_p99": self.ttft_p99,
                "last_heartbeat_age_s": round(
                    self._clock() - self.last_heartbeat, 3),
            }


class HealthMonitor:
    """The probe thread: every ``interval_s`` it (a) checks each
    replica's process liveness, (b) GETs its stats through the
    ``router.health_probe`` seam, (c) feeds the health record, and
    (d) fires ``on_dead(replica)`` exactly once when a replica is gone
    (probe failed AND the process/flag says dead) so the router can
    resubmit its in-flight work and the manager can spawn a
    replacement.

    ``suspect_queue_depth`` / ``suspect_ttft_p99_s`` turn the
    heartbeat gauges into soft SUSPECT signals (overloaded ≠ broken).
    """

    def __init__(self, replicas, interval_s=0.25, on_dead=None,
                 on_sweep=None, suspect_queue_depth=32,
                 suspect_ttft_p99_s=None):
        self._replicas = replicas          # callable -> iterable of handles
        self.interval_s = float(interval_s)
        self._on_dead = on_dead
        self._on_sweep = on_sweep          # post-sweep bookkeeping hook
        self.suspect_queue_depth = suspect_queue_depth
        self.suspect_ttft_p99_s = suspect_ttft_p99_s
        self._stop = threading.Event()
        self._thread = None
        self._dead_fired: set = set()

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="mxnet-fleet-health", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def poll_once(self):
        """One probe sweep (the thread loop body; tests call it
        directly for deterministic stepping)."""
        for replica in list(self._replicas()):
            h = replica.health
            h.tick()
            if not replica.alive():
                h.note_failure(reason="process dead")
                self._fire_dead(replica)
                continue
            try:
                stats = replica.probe()
            except BaseException as e:
                h.note_failure(reason=f"probe: {e!r}")
                if not replica.alive():
                    self._fire_dead(replica)
                continue
            qd = stats.get("queue_depth") if stats else None
            p99 = ((stats.get("ttft_s") or {}).get("p99")
                   if stats else None)
            h.note_heartbeat(queue_depth=qd, ttft_p99=p99)
            h.note_success()
            if qd is not None and self.suspect_queue_depth and \
                    qd >= self.suspect_queue_depth:
                h.note_suspect(f"queue depth {qd}")
            if p99 is not None and self.suspect_ttft_p99_s and \
                    p99 >= self.suspect_ttft_p99_s:
                h.note_suspect(f"ttft p99 {p99:.3f}s")
        if self._on_sweep is not None:
            try:
                self._on_sweep()
            except Exception:
                _LOGGER.exception("health monitor on_sweep hook failed")

    def _fire_dead(self, replica):
        if id(replica) in self._dead_fired:
            return
        self._dead_fired.add(id(replica))
        _LOGGER.warning("fleet: replica %s detected dead", replica.rid)
        if self._on_dead is not None:
            try:
                self._on_dead(replica)
            except Exception:
                _LOGGER.exception("on_dead handler failed for %s",
                                  replica.rid)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                _LOGGER.exception("health monitor sweep failed")
