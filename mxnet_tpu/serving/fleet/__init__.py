"""Serving fleet: a router in front of N engine replicas.

The single-process :class:`~mxnet_tpu.serving.engine.ServingEngine`
answers "how do I serve this model"; this package answers "how do I
keep serving when a replica dies".  A router process (zero device
work, zero jax imports — enforced by tests and the MXT110 pass) fronts
N real engine replicas and owns:

- **health** (health.py): per-replica HEALTHY → SUSPECT → EJECTED →
  PROBING state machine fed by heartbeats, load gauges, and dispatch
  outcomes; a circuit breaker ejects after consecutive failures and
  re-admits through bounded half-open probe traffic.
- **reliable dispatch** (router.py + transport.py): every request
  carries an id and an absolute deadline; transient failures retry
  under the shared fault.py budget; tail requests get ONE hedged
  duplicate after a p99-derived delay with first-winner-cancels-loser
  dedup; prompt-prefix rendezvous hashing keeps shared-prefix traffic
  on KV-warm replicas and falls back cleanly on ejection.
- **failure recovery**: a SIGKILLed replica is detected within one
  probe interval; its in-flight requests are resubmitted to survivors
  exactly once (idempotency ledger — no completion is ever delivered
  twice); the manager spawns a warm replacement through the shared
  compile cache / ``join_replica`` donation path.
- **graceful degradation** (policy.py): deficit-round-robin fair-share
  admission per tenant; deadline-aware shedding (429 + Retry-After
  from the observed drain rate) when the fleet-wide queue breaches its
  SLO; debounced scale-up/down hooks driven by queue and goodput
  breaches.

Chaos enters through four fault seams — ``router.dispatch``,
``router.health_probe``, ``fleet.spawn``, ``replica.crash`` — so every
recovery path above is exercisable deterministically in tests.
"""
from __future__ import annotations

from .health import (EJECTED, HEALTHY, PROBING, SUSPECT, HealthMonitor,
                     ReplicaHealth)
from .manager import FleetManager, ProcessReplica, serve_fleet
from .policy import (Autoscaler, FairShareQueue, HedgePolicy,
                     SheddingPolicy, prefix_key, rendezvous_order)
from .router import (FleetBusyError, FleetRequest, IdempotencyLedger,
                     LocalReplica, ReplicaHandle, Router)
from .transport import (ReplicaHTTPError, TransportError, call_local,
                        get_json, post_json, remaining_s)

__all__ = [
    "HEALTHY", "SUSPECT", "EJECTED", "PROBING", "ReplicaHealth",
    "HealthMonitor",
    "FairShareQueue", "HedgePolicy", "SheddingPolicy", "Autoscaler",
    "prefix_key", "rendezvous_order",
    "Router", "FleetRequest", "FleetBusyError", "IdempotencyLedger",
    "ReplicaHandle", "LocalReplica",
    "FleetManager", "ProcessReplica", "serve_fleet",
    "TransportError", "ReplicaHTTPError", "post_json", "get_json",
    "call_local", "remaining_s",
]
