"""The seam-wrapped router→replica transport funnel.

Every dispatch and health-probe the fleet router makes flows through
the three functions here — :func:`post_json`, :func:`get_json`, and
:func:`call_local` — and nowhere else (the MXT110 ``fleet-discipline``
pass enforces both halves: no raw socket/HTTP sends elsewhere in
fleet/, and every funnel call site carries an explicit ``deadline``).
Funneling buys three invariants at one choke point:

- **chaos**: the ``router.dispatch`` / ``router.health_probe`` fault
  seams are checked here, inside the retried region, so an armed trip
  is absorbed exactly like a real network failure;
- **deadlines**: ``deadline`` is an absolute ``time.monotonic()``
  second count and is *required* — a dispatch with no deadline would
  wedge a dispatcher thread on a dead replica forever;
- **retry policy**: transient failures ride the shared fault.py
  ``call_with_retries`` full-jitter policy, bounded per call by
  ``retries`` (the router passes its per-request budget).

This module never imports jax: the router does zero device work.
"""
from __future__ import annotations

import json
import time

from ... import fault as _fault
from ...base import MXNetError

__all__ = ["TransportError", "ReplicaHTTPError", "post_json", "get_json",
           "call_local", "remaining_s"]


class TransportError(ConnectionError):
    """Router→replica transport failure (connect/send/receive).  A
    subclass of ConnectionError on purpose: ``fault.is_transient``
    classifies it retryable with no special-casing."""


class ReplicaHTTPError(MXNetError):
    """The replica answered with a non-2xx status.  NOT transient (the
    reply proves the replica is alive); carries ``status`` and the
    decoded ``body`` so the router can relay 429/4xx semantics."""

    def __init__(self, status, body):
        super().__init__(f"replica HTTP {status}: {str(body)[:200]}")
        self.status = int(status)
        self.body = body


def remaining_s(deadline):
    """Seconds left until an absolute monotonic ``deadline`` (raises
    TimeoutError — transient, so retry accounting stays uniform — when
    it already passed)."""
    left = float(deadline) - time.monotonic()
    if left <= 0:
        raise TimeoutError("deadline exceeded before send")
    return left


def _http_round_trip(host, port, method, path, payload, deadline):
    # the ONE raw-HTTP site in the fleet package (MXT110's funnel)
    import http.client

    body = None
    if payload is not None:
        body = json.dumps(payload).encode()
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=remaining_s(deadline))
    try:
        try:
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise TransportError(f"{method} {host}:{port}{path}: "
                                 f"{e!r}") from e
    finally:
        conn.close()
    try:
        doc = json.loads(data) if data else None
    except ValueError:
        doc = {"raw": data.decode(errors="replace")}
    if resp.status >= 300:
        raise ReplicaHTTPError(resp.status, doc)
    return doc, dict(resp.getheaders())


def post_json(host, port, path, payload, *, deadline,
              seam="router.dispatch", retries=0, logger=None):
    """POST ``payload`` as JSON and return the decoded JSON reply.

    ``deadline`` (absolute monotonic seconds) is mandatory and bounds
    every attempt's socket timeout; ``retries`` bounds transient
    re-sends under the shared full-jitter backoff.  The ``seam`` check
    sits inside the retried region."""
    doc, _ = _fault.call_with_retries(
        seam, _http_round_trip, host, port, "POST", path, payload,
        deadline, retries=retries, logger=logger)
    return doc


def get_json(host, port, path, *, deadline, seam="router.health_probe",
             retries=0, logger=None):
    """GET and return the decoded JSON reply (probe path: ``retries``
    defaults to 0 — a failed probe is *data* for the health state
    machine, not something to paper over)."""
    doc, _ = _fault.call_with_retries(
        seam, _http_round_trip, host, port, "GET", path, None,
        deadline, retries=retries, logger=logger)
    return doc


def call_local(fn, *args, deadline, seam="router.dispatch", retries=0,
               logger=None, **kwargs):
    """The in-process leg of the funnel: run ``fn`` under the same
    seam/deadline/retry contract the HTTP legs get, for
    ``LocalReplica`` fleets (unit tests, single-process embedders).
    ``fn`` receives the deadline via its own closure; this wrapper
    enforces it is not already past and arms the seam."""
    remaining_s(deadline)    # fail fast, uniformly with the HTTP legs
    return _fault.call_with_retries(seam, fn, *args, retries=retries,
                                    logger=logger, **kwargs)
