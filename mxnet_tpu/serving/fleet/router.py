"""The fleet router: reliable dispatch over N serving replicas.

One router process fronts N real :class:`ServingEngine` replicas.  The
router is pure host-side control plane — no jax import, no device
work, no fresh traces on any replica (it only ever calls routes the
replicas already serve).  What it adds over a bare replica:

- **reliable dispatch**: every router→replica request carries the
  fleet request id and an absolute deadline; transient transport
  failures retry under ``MXNET_FLEET_RETRY_BUDGET`` (the fault.py
  full-jitter policy), and a replica that fails mid-request gets the
  request failed over to a peer through the fair-share queue.
- **hedging**: a request still unanswered after the observed ~p99
  dispatch latency (floored by ``MXNET_FLEET_HEDGE_MS``) gets ONE
  duplicate on the next replica in its affinity order; the first
  completion claims the :class:`IdempotencyLedger` and the loser's
  result is dropped — a completion is never delivered twice.
- **prefix affinity**: requests hash by prompt prefix
  (:func:`policy.rendezvous_order`), so shared-prompt traffic hits
  the replica whose KV cache is warm; ejection falls back to the next
  rank of the SAME ordering, no global remap.
- **failure recovery**: the health monitor detects a SIGKILLed
  replica within one probe interval; its in-flight requests are
  popped (each can be requeued at most once per death — the atomic
  ``try_requeue`` state transition guarantees no double-resubmit even
  when the dispatch thread sees the connection error concurrently)
  and resubmitted to survivors at the front of the queue.
- **graceful degradation**: per-tenant fair-share admission, and
  deadline-aware shedding — when the fleet-wide queue breaches the
  SLO depth (or the projected wait exceeds the caller's deadline),
  submit fails as a 429 with an honest Retry-After.

Chaos seams on every path: ``router.dispatch`` (transport funnel),
``router.health_probe`` (monitor), ``replica.crash`` (replica-side
request loop), ``fleet.spawn`` (manager).
"""
from __future__ import annotations

import itertools
import json
import logging
import threading
import time

from ... import env as _env
from ... import fault as _fault
from ... import lifecycle as _lifecycle
from ... import telemetry as _telemetry
from ...base import MXNetError
from ..scheduler import DeadlineExceededError, QueueFullError
from ..tracing import RequestTrace, TraceStore
from . import transport as _transport
from .health import EJECTED, HEALTHY, PROBING, SUSPECT, HealthMonitor, \
    ReplicaHealth
from .policy import Autoscaler, FairShareQueue, HedgePolicy, \
    SheddingPolicy, prefix_key, rendezvous_order

__all__ = ["FleetRequest", "FleetBusyError", "IdempotencyLedger",
           "ReplicaHandle", "LocalReplica", "Router"]

_LOGGER = logging.getLogger(__name__)

# -- metric families (README "Metric catalog" has the rows) ----------------
_C_DISPATCH = _telemetry.counter(
    "mxnet_fleet_dispatches_total",
    "router→replica dispatch attempts by outcome",
    labelnames=("outcome",))
_C_HEDGES = _telemetry.counter(
    "mxnet_fleet_hedges_total",
    "hedged duplicate requests by outcome (won = the hedge delivered)",
    labelnames=("outcome",))
_C_RESUBMITS = _telemetry.counter(
    "mxnet_fleet_resubmits_total",
    "in-flight requests resubmitted to survivors after a replica death")
_C_DUP = _telemetry.counter(
    "mxnet_fleet_duplicates_suppressed_total",
    "late/duplicate completions dropped by the idempotency ledger")
_C_SHED = _telemetry.counter(
    "mxnet_fleet_shed_total",
    "requests 429'd by deadline-aware shedding (Retry-After attached)")
_G_REPLICAS = _telemetry.gauge(
    "mxnet_fleet_replicas", "fleet replicas by health state",
    labelnames=("state",))
_G_FLEET_QUEUE = _telemetry.gauge(
    "mxnet_fleet_queue_depth",
    "requests waiting in the router's fair-share queue")
_H_DISPATCH = _telemetry.histogram(
    "mxnet_fleet_dispatch_seconds",
    "router→replica round-trip latency (successful dispatches; feeds "
    "the hedge-delay p99)")

_RID = itertools.count(1)


class FleetBusyError(QueueFullError):
    """Fleet-wide backpressure (HTTP 429): the queue SLO is breached
    or the projected wait exceeds the request's deadline.  Carries the
    drain-rate-derived ``retry_after_s``."""

    def __init__(self, message, retry_after_s):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class FleetRequest:
    """One request as the ROUTER sees it: payload + deadline + a
    three-state lifecycle (queued → inflight → done) whose transitions
    are atomic — that atomicity is what makes crash resubmission
    exactly-once (the death handler and a concurrently-failing
    dispatch thread both try ``try_requeue``; one wins)."""

    __slots__ = ("id", "tenant", "prompt", "max_new_tokens",
                 "temperature", "eos_id", "deadline", "submitted",
                 "affinity", "state", "result", "error", "attempts",
                 "hedges", "resubmits", "trace", "on_resolve", "_done",
                 "_state_lock", "finished_t")

    def __init__(self, prompt, tenant="default", max_new_tokens=16,
                 temperature=0.0, eos_id=None, deadline_ms=30_000):
        self.id = next(_RID)
        self.tenant = str(tenant)
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise MXNetError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        now = time.monotonic()
        self.submitted = now
        # EVERY fleet request has a deadline: an unbounded dispatch
        # would wedge a dispatcher thread on a dead replica forever
        self.deadline = now + max(1, int(deadline_ms)) / 1e3
        self.affinity = prefix_key(self.prompt)
        self.state = "queued"
        self.result = None
        self.error = None
        self.attempts = 0
        self.hedges = 0
        self.resubmits = 0
        self.trace = None
        self.on_resolve = None
        self.finished_t = None
        self._done = threading.Event()
        self._state_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def try_inflight(self):
        with self._state_lock:
            if self.state == "queued":
                self.state = "inflight"
                return True
            return False

    def try_requeue(self):
        """Atomically move inflight → queued (crash resubmission /
        dispatch failover).  Exactly one of the racing callers — the
        death handler popping the replica's in-flight set, or the
        dispatch thread seeing the connection die — wins."""
        with self._state_lock:
            if self.state == "inflight":
                self.state = "queued"
                return True
            return False

    def resolve(self, result=None, error=None):
        with self._state_lock:
            if self.state == "done":
                return False
            self.state = "done"
        self.result = result
        self.error = error
        self.finished_t = time.monotonic()
        hook = self.on_resolve
        if hook is not None:
            try:
                hook(self)
            except Exception:   # tracing must never fail a request
                pass
        self._done.set()
        return True

    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def expired(self, now=None):
        return (now if now is not None else time.monotonic()) > \
            self.deadline

    def remaining_s(self, now=None):
        return self.deadline - (now if now is not None
                                else time.monotonic())

    def response(self, timeout=None):
        """Block for the completion dict (raises the stored error)."""
        if not self.wait(timeout):
            raise MXNetError(f"fleet request {self.id}: no result "
                             f"within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class IdempotencyLedger:
    """At-most-once completion delivery, keyed on the fleet request
    id.  The FIRST ``claim(rid)`` wins the right to resolve the
    request; hedged duplicates, late responses from a replica that
    was presumed dead, and the router's own deadline path all lose
    and drop their result.  Bounded: oldest claims are pruned past
    ``cap`` (a claim only matters while its request can still race)."""

    def __init__(self, cap=8192):
        self._cap = int(cap)
        self._claimed: dict = {}      # rid -> insertion order
        self._order: list = []
        self._lock = threading.Lock()
        self.duplicates = 0

    def claim(self, rid):
        with self._lock:
            if rid in self._claimed:
                self.duplicates += 1
                return False
            self._claimed[rid] = True
            self._order.append(rid)
            while len(self._order) > self._cap:
                self._claimed.pop(self._order.pop(0), None)
            return True

    def stats(self):
        with self._lock:
            return {"claimed": len(self._claimed),
                    "duplicates_suppressed": self.duplicates}


class ReplicaHandle:
    """Base replica handle: identity, health record, and the
    in-flight map the crash-resubmission path drains.  Subclasses
    implement the transport (:class:`LocalReplica` in-process,
    ``manager.ProcessReplica`` over HTTP)."""

    def __init__(self, rid, eject_threshold=3, probe_interval_s=0.25):
        self.rid = str(rid)
        self.health = ReplicaHealth(
            eject_threshold=eject_threshold,
            cooldown_s=max(0.25, 2 * probe_interval_s))
        self._inflight: dict = {}
        self._if_lock = threading.Lock()

    def track(self, req):
        with self._if_lock:
            self._inflight[req.id] = req

    def untrack(self, req):
        with self._if_lock:
            self._inflight.pop(req.id, None)

    def drain_inflight(self):
        """Pop EVERYTHING in flight (death path).  Popping — not
        copying — is what bounds resubmission: each request leaves
        this replica's map exactly once per death."""
        with self._if_lock:
            reqs = list(self._inflight.values())
            self._inflight.clear()
        return reqs

    def inflight_count(self):
        with self._if_lock:
            return len(self._inflight)

    # subclass surface ----------------------------------------------------
    def alive(self):
        raise NotImplementedError

    def probe(self):
        raise NotImplementedError

    def submit(self, freq, retries=0):
        raise NotImplementedError

    def shutdown(self, drain=True, timeout=30):
        raise NotImplementedError

    def snapshot(self):
        return {"rid": self.rid, "alive": self.alive(),
                "inflight": self.inflight_count(),
                "health": self.health.snapshot()}


class LocalReplica(ReplicaHandle):
    """In-process replica: wraps a started :class:`ServingEngine`.
    The unit-test fleet and single-process embedders use this; the
    ``replica.crash`` chaos seam lives on its request path (an armed
    trip kills the replica mid-request — in-flight work is recovered
    by the same detect→resubmit machinery a SIGKILL exercises)."""

    def __init__(self, rid, engine, **kw):
        super().__init__(rid, **kw)
        self._engine = engine
        self._alive = True

    @property
    def engine(self):
        return self._engine

    def alive(self):
        return self._alive and self._engine.running()

    def kill(self):
        """Simulated SIGKILL: the handle goes dark instantly; requests
        blocked inside resolve with an abort error."""
        self._alive = False
        try:
            self._engine.close(drain=False, timeout=5)
        except Exception:
            pass

    def probe(self):
        return _transport.call_local(
            self._probe_body, deadline=time.monotonic() + 1.0,
            seam="router.health_probe")

    def _probe_body(self):
        if not self.alive():
            raise ConnectionError(f"replica {self.rid} is down")
        return self._engine.stats()

    def submit(self, freq, retries=0):
        return _transport.call_local(
            self._submit_body, freq, deadline=freq.deadline,
            seam="router.dispatch", retries=retries)

    def _submit_body(self, freq):
        # the replica-side crash point: an armed trip takes the whole
        # replica down mid-request, exactly like a SIGKILL would —
        # the handle goes dark and the error surfaces as a transport
        # failure for the dispatch path to absorb
        try:
            _fault.check("replica.crash")
        except BaseException as e:
            self._alive = False
            raise ConnectionError(
                f"replica {self.rid} crashed mid-request ({e!r})") from e
        if not self.alive():
            raise ConnectionError(f"replica {self.rid} is down")
        req = self._engine.submit(
            freq.prompt, max_new_tokens=freq.max_new_tokens,
            temperature=freq.temperature, eos_id=freq.eos_id,
            deadline_ms=max(1, int(freq.remaining_s() * 1e3)),
            trace_id=freq.id)
        res = req.result(timeout=max(0.001, freq.remaining_s()))
        if req.trace is not None:
            res["trace"] = req.trace.to_dict()
        return res

    def shutdown(self, drain=True, timeout=30):
        self._alive = False
        try:
            self._engine.close(drain=drain, timeout=timeout)
        except Exception:
            pass


class Router:
    """The dispatch plane.  ``replicas`` is the initial handle list
    (the manager adds/removes live).  ``start()`` spins up the health
    monitor and ``dispatchers`` worker threads; ``submit()`` is the
    front door (`mount_http()` exposes it as ``/v1/completions``)."""

    def __init__(self, replicas=(), *, hedge_ms=None, retry_budget=None,
                 probe_interval_ms=None, queue_bound=256,
                 tenant_bound=64, shed_depth=None, tenant_weights=None,
                 default_deadline_ms=30_000, dispatchers=None,
                 manager=None, autoscale=None, trace_requests=None):
        self._replicas: list = list(replicas)
        self._rep_lock = threading.Lock()
        self._manager = manager
        self._retry_budget = retry_budget if retry_budget is not None \
            else _env.fleet_retry_budget()
        self._probe_interval_s = (
            probe_interval_ms if probe_interval_ms is not None
            else _env.fleet_probe_interval_ms()) / 1e3
        self._default_deadline_ms = int(default_deadline_ms)
        self._queue = FairShareQueue(queue_bound, tenant_bound,
                                     weights=tenant_weights)
        self._hedge = HedgePolicy(
            floor_ms=hedge_ms if hedge_ms is not None
            else _env.fleet_hedge_ms())
        self._ledger = IdempotencyLedger()
        self._shed = SheddingPolicy(
            slo_depth=shed_depth if shed_depth is not None
            else max(8, int(queue_bound) // 2))
        self._shed_episode = 0        # 429s in the current breach episode
        self._autoscaler = autoscale
        self._monitor = HealthMonitor(
            self.replicas, interval_s=self._probe_interval_s,
            on_dead=self._on_replica_dead, on_sweep=self._after_sweep)
        self._trace_enabled = bool(
            trace_requests if trace_requests is not None
            else _env.trace_requests())
        self._traces = TraceStore()
        self._n_dispatchers = int(dispatchers) if dispatchers else \
            max(2, 2 * max(1, len(self._replicas)))
        self._threads: list = []
        self._stop_evt = threading.Event()
        self._mounted: list = []

    # -- replica set -------------------------------------------------------
    def replicas(self):
        with self._rep_lock:
            return list(self._replicas)

    def add_replica(self, handle):
        with self._rep_lock:
            self._replicas.append(handle)

    def remove_replica(self, handle):
        with self._rep_lock:
            if handle in self._replicas:
                self._replicas.remove(handle)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._threads:
            return self
        self._stop_evt.clear()
        self._monitor.start()
        for i in range(self._n_dispatchers):
            t = threading.Thread(target=self._dispatch_loop,
                                 name=f"mxnet-fleet-dispatch-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        if self._autoscaler is not None:
            _lifecycle.register_goodput_breach_hook(
                self._autoscaler.note_goodput_breach)
        return self

    def close(self, drain=True, timeout=30):
        self._stop_evt.set()
        self._monitor.stop()
        if self._autoscaler is not None:
            _lifecycle.unregister_goodput_breach_hook(
                self._autoscaler.note_goodput_breach)
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []
        n = self._queue.drain(lambda r: MXNetError(
            f"fleet request {r.id} rejected: router shutting down"))
        for _ in range(n):
            _C_DISPATCH.labels(outcome="shutdown").inc()
        self.unmount_http()

    # -- front door --------------------------------------------------------
    def submit(self, prompt, tenant="default", max_new_tokens=16,
               temperature=0.0, eos_id=None, deadline_ms=None):
        """Admit one request into the fair-share queue.  Raises
        :class:`FleetBusyError` (429 + Retry-After) when the fleet
        queue breaches the SLO depth or the projected wait already
        exceeds the caller's deadline — shedding at admission, where
        the caller can still go elsewhere."""
        if self._stop_evt.is_set():
            raise MXNetError("fleet router is shutting down")
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self._default_deadline_ms
        depth = len(self._queue)
        shed_reason = None
        if self._shed.should_shed(depth):
            shed_reason = f"fleet queue depth {depth} breaches the " \
                f"SLO ({self._shed.slo_depth})"
        else:
            rate = self._shed.drain_rate()
            if rate and depth / rate > deadline_ms / 1e3:
                shed_reason = (
                    f"projected wait {depth / rate:.1f}s exceeds the "
                    f"{deadline_ms / 1e3:.1f}s deadline")
        if shed_reason is not None:
            ra = self._shed.retry_after_s(depth)
            _C_SHED.inc()
            self._note_shed(depth)
            raise FleetBusyError(f"shed: {shed_reason}; retry after "
                                 f"{ra:.0f}s", retry_after_s=ra)
        req = FleetRequest(prompt, tenant=tenant,
                           max_new_tokens=max_new_tokens,
                           temperature=temperature, eos_id=eos_id,
                           deadline_ms=deadline_ms)
        if self._trace_enabled:
            req.trace = RequestTrace(req.id)
            req.trace.event("submitted", tenant=req.tenant,
                            prompt_len=len(req.prompt),
                            affinity=req.affinity)
            req.on_resolve = self._trace_finished
        self._queue.put(req, tenant=req.tenant)
        _G_FLEET_QUEUE.set(len(self._queue))
        return req

    def _note_shed(self, depth):
        # one lifecycle alert per breach EPISODE, not per shed request
        self._shed_episode += 1
        if self._shed_episode == 1:
            _lifecycle.note_fleet_queue_slo_breach(
                depth, self._shed.slo_depth, self._shed_episode)
        if self._autoscaler is not None:
            self._autoscaler.note_queue_breach(depth)

    # -- dispatch ----------------------------------------------------------
    def _dispatch_loop(self):
        while not self._stop_evt.is_set():
            req = self._queue.pop_ready(
                is_expired=lambda r: r.expired(),
                on_expire=self._expire)
            if req is None:
                self._queue.wait_nonempty(0.02)
                continue
            try:
                self._dispatch_one(req)
            except Exception as e:
                _LOGGER.exception("dispatch failed for request %s",
                                  req.id)
                if self._ledger.claim(req.id):
                    req.resolve(error=MXNetError(
                        f"fleet request {req.id} failed in dispatch: "
                        f"{e!r}"))
                    _C_DISPATCH.labels(outcome="error").inc()
            _G_FLEET_QUEUE.set(len(self._queue))

    def _expire(self, req):
        if self._ledger.claim(req.id):
            if req.trace is not None:
                req.trace.event("deadline_expired", where="fleet_queue")
            req.resolve(error=DeadlineExceededError(
                f"fleet request {req.id} expired after "
                f"{time.monotonic() - req.submitted:.3f}s in queue"))
            _C_DISPATCH.labels(outcome="expired").inc()

    def _pick_order(self, req):
        """Affinity-ordered dispatchable replicas: rendezvous rank of
        the prompt-prefix key, ejected/dead replicas filtered — the
        fallback when the warm home is ejected is simply the next
        rank, and it is the SAME for every request sharing the key."""
        reps = {r.rid: r for r in self.replicas()}
        order = rendezvous_order(req.affinity, sorted(reps))
        live = [reps[rid] for rid in order
                if reps[rid].alive() and reps[rid].health.dispatchable()]
        # SUSPECT replicas (overloaded or freshly failing) sink below
        # every non-suspect peer — stably, so rendezvous rank still
        # decides within each class
        return sorted(live, key=lambda r: r.health.state == SUSPECT)

    def _dispatch_one(self, req):
        tr = req.trace
        if tr is not None:
            tr.add_span("queue_wait", tr.last_enqueue_t,
                        time.perf_counter(), tenant=req.tenant)
        order = self._pick_order(req)
        if not order:
            if req.expired():
                self._expire(req)
                return
            # nothing dispatchable right now (all ejected / mid-spawn):
            # brief pause, then back to the FRONT — age order holds
            time.sleep(min(0.05, max(0.0, req.remaining_s())))
            if tr is not None:
                tr.event("requeued", reason="no dispatchable replica")
                tr.last_enqueue_t = time.perf_counter()
            self._queue.requeue(req, tenant=req.tenant)
            return
        if not req.try_inflight():
            return      # resolved while queued (expiry race)
        primary = order[0]
        t = threading.Thread(
            target=self._attempt, args=(req, primary, "primary"),
            name=f"mxnet-fleet-attempt-{req.id}", daemon=True)
        t.start()
        # the hedge window: wait ~p99; a healthy dispatch finishes
        # well inside it and no duplicate is ever sent
        hedged = False
        delay = min(self._hedge.delay_s(), max(0.0, req.remaining_s()))
        if not req.wait(delay) and not req.expired() \
                and req.state == "inflight" and len(order) > 1:
            req.hedges += 1
            hedged = True
            if tr is not None:
                tr.event("hedged", replica=order[1].rid,
                         after_s=round(delay, 4))
            self._attempt(req, order[1], "hedge")
        # ride out the deadline; a failed attempt may have requeued
        # the request (state back to "queued"), in which case another
        # dispatcher owns it from here
        while not req.done() and req.state == "inflight":
            if req.expired():
                if self._ledger.claim(req.id):
                    req.resolve(error=DeadlineExceededError(
                        f"fleet request {req.id} missed its deadline "
                        f"in dispatch (attempts={req.attempts}, "
                        f"hedged={hedged})"))
                    _C_DISPATCH.labels(outcome="expired").inc()
                break
            req.wait(0.02)

    def _attempt(self, req, replica, kind):
        """One router→replica try (primary or hedge).  Success claims
        the ledger; failure feeds the health breaker and — atomically,
        at most once — requeues the request for failover."""
        h = replica.health
        if not h.try_acquire_probe():
            # half-open budget exhausted: treat like a miss, failover
            if not req.done() and req.try_requeue():
                self._requeue_front(req, "probe budget")
            return
        replica.track(req)
        req.attempts += 1
        t0 = time.perf_counter()
        try:
            res = replica.submit(req, retries=self._retry_budget)
        except BaseException as e:
            replica.untrack(req)
            h.release_probe()
            h.note_failure(reason=f"{kind}: {type(e).__name__}")
            _C_DISPATCH.labels(outcome="failed").inc()
            if kind == "hedge":
                _C_HEDGES.labels(outcome="failed").inc()
            if req.trace is not None:
                req.trace.event("dispatch_failed", replica=replica.rid,
                                kind=kind, error=repr(e)[:160])
            if not req.done() and req.try_requeue():
                self._requeue_front(req, f"dispatch failure on "
                                    f"{replica.rid}")
            return
        replica.untrack(req)
        h.release_probe()
        h.note_success()
        dt = time.perf_counter() - t0
        self._hedge.observe(dt)
        _H_DISPATCH.observe(dt)
        self._shed.note_completion()
        self._shed_episode = 0
        if self._ledger.claim(req.id):
            if req.trace is not None:
                attrs = {"replica": replica.rid, "kind": kind}
                rep_tree = res.pop("trace", None) if \
                    isinstance(res, dict) else None
                if rep_tree is not None:
                    # cross-process graft: the replica's span tree rides
                    # the dispatch span (its clock is the REPLICA's
                    # perf_counter — honest attachment, not a rebase)
                    attrs["replica_trace"] = rep_tree
                req.trace.add_span("dispatch", t0, time.perf_counter(),
                                   **attrs)
            req.resolve(result=res)
            _C_DISPATCH.labels(outcome="ok").inc()
            if kind == "hedge":
                _C_HEDGES.labels(outcome="won").inc()
        else:
            if isinstance(res, dict):
                res.pop("trace", None)
            _C_DUP.inc()
            if kind == "hedge":
                _C_HEDGES.labels(outcome="lost").inc()

    def _requeue_front(self, req, reason):
        if req.trace is not None:
            req.trace.event("requeued", reason=reason)
            req.trace.last_enqueue_t = time.perf_counter()
        self._queue.requeue(req, tenant=req.tenant)

    # -- failure recovery --------------------------------------------------
    def _on_replica_dead(self, replica):
        """Health monitor verdict: the replica is gone.  Pop its
        in-flight map and resubmit every unresolved request to the
        survivors — exactly once each (the pop removes it from this
        replica forever; ``try_requeue`` arbitrates against the racing
        dispatch thread)."""
        victims = replica.drain_inflight()
        n = 0
        for req in victims:
            if req.done():
                continue
            if req.try_requeue():
                n += 1
                req.resubmits += 1
                _C_RESUBMITS.inc()
                if req.trace is not None:
                    req.trace.event("resubmit_after_crash",
                                    replica=replica.rid)
                    req.trace.last_enqueue_t = time.perf_counter()
                self._queue.requeue(req, tenant=req.tenant)
        _LOGGER.warning(
            "fleet: replica %s dead; resubmitted %d in-flight "
            "request(s) to survivors", replica.rid, n)
        if self._manager is not None:
            self._manager.on_replica_dead(replica)

    # -- bookkeeping (health-monitor sweep cadence) ------------------------
    def _after_sweep(self):
        counts = {HEALTHY: 0, SUSPECT: 0, EJECTED: 0, PROBING: 0}
        for r in self.replicas():
            counts[r.health.state] = counts.get(r.health.state, 0) + 1
        for state, n in counts.items():
            _G_REPLICAS.labels(state=state).set(n)
        _G_FLEET_QUEUE.set(len(self._queue))
        if self._autoscaler is not None:
            self._autoscaler.note_tick(len(self._queue))

    # -- tracing -----------------------------------------------------------
    def _trace_finished(self, req):
        tr = req.trace
        if tr is None:
            return
        err = req.error
        if err is None:
            outcome = "done"
        elif isinstance(err, DeadlineExceededError):
            outcome = "expired"
        else:
            outcome = "error"
        tr.finish(outcome, error=err)
        self._traces.add(tr)
        tr.emit_chrome()

    # -- observability -----------------------------------------------------
    def stats(self):
        reps = self.replicas()
        return {
            "replicas": [r.snapshot() for r in reps],
            "queue_depth": len(self._queue),
            "queue_by_tenant": self._queue.depths(),
            "hedge_delay_s": round(self._hedge.delay_s(), 4),
            "retry_budget": self._retry_budget,
            "shed": {"slo_depth": self._shed.slo_depth,
                     "drain_rate": self._shed.drain_rate()},
            "ledger": self._ledger.stats(),
            "request_traces": {"enabled": self._trace_enabled,
                               "traced": self._traces.count()},
        }

    # -- HTTP plane --------------------------------------------------------
    def mount_http(self, prefix="/v1"):
        """Mount the fleet front door beside /metrics: POST
        ``{prefix}/completions`` (the same body schema a single
        replica serves, plus ``tenant``), GET ``{prefix}/fleet``
        (health/queue snapshot), GET ``{prefix}/requests`` (router
        trace store — each trace carries the grafted replica tree)."""
        comp, flt = prefix + "/completions", prefix + "/fleet"
        reqs = prefix + "/requests"
        _telemetry.register_http_route(comp, self._http_completions)
        _telemetry.register_http_route(flt, self._http_fleet)
        _telemetry.register_http_route(reqs, self._http_requests)
        self._mounted = [comp, flt, reqs]
        return self

    def unmount_http(self):
        for path in self._mounted:
            _telemetry.unregister_http_route(path)
        self._mounted = []

    def _http_fleet(self, method, path, query, body):
        return 200, "application/json", json.dumps(self.stats()).encode()

    def _http_requests(self, method, path, query, body):
        doc = self._traces.snapshot()
        doc["enabled"] = self._trace_enabled
        return 200, "application/json", json.dumps(doc).encode()

    def _http_completions(self, method, path, query, body):
        if method != "POST":
            return 405, "application/json", b'{"error": "POST only"}'
        try:
            data = json.loads(body or b"{}")
            prompt = data["prompt"]
        except (ValueError, KeyError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"bad request: {e!r}"}).encode()
        try:
            req = self.submit(
                prompt, tenant=str(data.get("tenant", "default")),
                max_new_tokens=int(data.get("max_new_tokens", 16)),
                temperature=float(data.get("temperature", 0.0)),
                eos_id=data.get("eos_id"),
                deadline_ms=data.get("deadline_ms"))
        except FleetBusyError as e:
            return (429, "application/json",
                    json.dumps({"error": str(e),
                                "retry_after_s": e.retry_after_s}
                               ).encode(),
                    {"Retry-After": max(1, int(e.retry_after_s))})
        except QueueFullError as e:
            return (429, "application/json",
                    json.dumps({"error": str(e)}).encode(),
                    {"Retry-After": 1})
        except MXNetError as e:
            return 400, "application/json", json.dumps(
                {"error": str(e)}).encode()
        try:
            res = req.response(timeout=req.remaining_s() + 1.0)
        except DeadlineExceededError as e:
            return 408, "application/json", json.dumps(
                {"error": str(e)}).encode()
        except MXNetError as e:
            return 503, "application/json", json.dumps(
                {"error": str(e)}).encode()
        out = dict(res) if isinstance(res, dict) else {"result": res}
        out["fleet"] = {"request_id": req.id, "attempts": req.attempts,
                        "hedges": req.hedges,
                        "resubmits": req.resubmits}
        return 200, "application/json", json.dumps(out).encode()
