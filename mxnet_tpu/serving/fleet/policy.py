"""Router policy: fair-share admission, hedging, prefix affinity,
deadline-aware shedding, and SLO-driven scaling.

All pure host-side data structures — no jax, no sockets (transport.py
owns the wire).  Each class is independently unit-testable with an
injected clock:

- :class:`FairShareQueue` generalizes the engine's ``AdmissionQueue``
  to per-tenant fairness: deficit-round-robin across tenant FIFOs, so
  one chatty tenant cannot starve the rest, with both per-tenant and
  global bounds (the global bound is the backpressure signal the
  shedding policy watches).
- :class:`HedgePolicy` turns the observed dispatch-latency tail into
  the hedge trigger: a request still unanswered after ~p99 gets ONE
  duplicate on a different replica (``MXNET_FLEET_HEDGE_MS`` floors
  the delay so cold windows do not hedge everything).
- :func:`rendezvous_order` is highest-random-weight hashing of the
  prompt-prefix key over replica ids: shared-prompt traffic lands on
  the replica whose KV cache is warm, and when that replica is
  ejected the SAME ordering yields the fallback (no remap churn of
  unrelated keys — the property consistent-hash schemes exist for).
- :class:`SheddingPolicy` answers "admit or 429" from the fleet-wide
  queue depth against the SLO threshold, with a Retry-After estimate
  derived from the observed drain rate.
- :class:`Autoscaler` debounces scale-up/down triggers (queue-SLO
  breaches, lifecycle goodput-breach events, sustained idleness) into
  the manager's spawn/drain hooks, with a cooldown so one burst does
  not thrash the fleet size.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque

from ..scheduler import QueueFullError

__all__ = ["FairShareQueue", "HedgePolicy", "prefix_key",
           "rendezvous_order", "SheddingPolicy", "Autoscaler"]


class FairShareQueue:
    """Deficit-round-robin admission across per-tenant FIFOs.

    Each tenant gets a deque and a deficit counter topped up by
    ``quantum × weight`` per service round; a request costs 1.  With
    equal weights this is strict round-robin between active tenants —
    a tenant submitting 1000 requests interleaves 1:1 with a tenant
    submitting 2, which is exactly the fairness ``AdmissionQueue``'s
    single FIFO cannot give.  ``requeue`` (crash resubmission /
    eviction) is bound-exempt and goes to the tenant's FRONT: that
    work was already admitted once."""

    def __init__(self, bound=256, tenant_bound=64, weights=None):
        self._bound = int(bound)
        self._tenant_bound = int(tenant_bound)
        self._weights = dict(weights or {})
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: OrderedDict = OrderedDict()   # tenant -> deque
        self._deficit: dict = {}
        self._total = 0

    def __len__(self):
        with self._lock:
            return self._total

    def depths(self):
        with self._lock:
            return {t: len(q) for t, q in self._queues.items()}

    def put(self, req, tenant="default"):
        with self._lock:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._deficit[tenant] = 0
            if self._total >= self._bound:
                raise QueueFullError(
                    f"fleet queue full ({self._bound} waiting)")
            if len(q) >= self._tenant_bound:
                raise QueueFullError(
                    f"tenant {tenant!r} queue full "
                    f"({self._tenant_bound} waiting)")
            q.append(req)
            self._total += 1
            self._cond.notify()

    def requeue(self, req, tenant="default"):
        """Front-of-line, bound-exempt re-admission (resubmit after a
        replica death, or a failed dispatch worth another pass)."""
        with self._lock:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._deficit[tenant] = 0
            q.appendleft(req)
            self._total += 1
            self._cond.notify()

    def pop_ready(self, is_expired=None, on_expire=None):
        """Next request in DRR order; entries failing ``is_expired``
        are handed to ``on_expire`` (outside the lock — the callback
        resolves futures and touches metrics) and skipped.  None when
        empty."""
        expired: list = []
        out = None
        with self._lock:
            while self._total > 0:
                req, _tenant = self._pop_drr()
                if req is None:
                    break
                if is_expired is not None and is_expired(req):
                    expired.append(req)
                    continue
                out = req
                break
        if on_expire is not None:
            for req in expired:
                on_expire(req)
        return out

    def _pop_drr(self):
        # caller holds the lock.  One full rotation visits every
        # non-empty tenant, topping deficits up by quantum×weight; the
        # first tenant whose deficit covers a cost-1 pop serves.
        for _ in range(2 * max(1, len(self._queues))):
            if not self._queues:
                return None, None
            tenant, q = next(iter(self._queues.items()))
            self._queues.move_to_end(tenant)
            if not q:
                continue
            self._deficit[tenant] += self._weights.get(tenant, 1)
            if self._deficit[tenant] >= 1:
                self._deficit[tenant] -= 1
                self._total -= 1
                return q.popleft(), tenant
        return None, None

    def wait_nonempty(self, timeout):
        with self._lock:
            if self._total:
                return True
            return self._cond.wait(timeout)

    def drain(self, error_factory):
        """Shutdown: resolve everything waiting with a clean error."""
        with self._lock:
            items = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
            self._total = 0
        for req in items:
            req.resolve(error_factory(req))
        return len(items)


class HedgePolicy:
    """p99-derived hedge trigger over a trailing dispatch-latency
    window.  Below ``min_samples`` observations the delay is the floor
    alone (an empty window must not hedge every request at 0ms)."""

    def __init__(self, floor_ms=50, window=512, min_samples=16):
        self.floor_s = max(0, int(floor_ms)) / 1e3
        self.min_samples = int(min_samples)
        self._lats: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    def observe(self, dt_s):
        with self._lock:
            self._lats.append(float(dt_s))

    def delay_s(self):
        with self._lock:
            lats = sorted(self._lats)
        if len(lats) < self.min_samples:
            return self.floor_s
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        return max(self.floor_s, p99)


def prefix_key(token_ids, k=16):
    """Affinity key for a prompt: digest of its first ``k`` tokens.
    Requests sharing a prompt prefix (system prompts, few-shot
    preambles) map to the same key, hence the same warm replica."""
    head = ",".join(str(int(t)) for t in list(token_ids)[:k])
    return hashlib.blake2b(head.encode(), digest_size=8).hexdigest()


def rendezvous_order(key, replica_ids):
    """Highest-random-weight ordering of ``replica_ids`` for ``key``:
    position 0 is the affinity home, position 1 the fallback when the
    home is ejected, and so on.  Removing one replica never reorders
    the others' relative ranks — traffic from a dead replica spreads
    without remapping everyone else's warm caches."""
    def score(rid):
        return hashlib.blake2b(f"{key}|{rid}".encode(),
                               digest_size=8).digest()

    return sorted(replica_ids, key=score, reverse=True)


class SheddingPolicy:
    """Deadline-aware admission gate on the FLEET-wide queue.

    Above ``slo_depth`` waiting requests the router stops admitting
    and answers 429 with a Retry-After derived from the observed drain
    rate (completions/s over a trailing window): an honest "come back
    when the backlog you see now has drained", clamped to
    [1, ``max_retry_after_s``]."""

    def __init__(self, slo_depth=128, window=128,
                 max_retry_after_s=30.0, clock=time.monotonic):
        self.slo_depth = int(slo_depth)
        self.max_retry_after_s = float(max_retry_after_s)
        self._clock = clock
        self._done_t: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    def note_completion(self):
        with self._lock:
            self._done_t.append(self._clock())

    def drain_rate(self):
        """Completions/s over the trailing window (None = no data)."""
        with self._lock:
            ts = list(self._done_t)
        if len(ts) < 2 or ts[-1] <= ts[0]:
            return None
        return (len(ts) - 1) / (ts[-1] - ts[0])

    def should_shed(self, queue_depth):
        return self.slo_depth > 0 and queue_depth >= self.slo_depth

    def retry_after_s(self, queue_depth):
        rate = self.drain_rate()
        if not rate:
            return 1.0
        return min(self.max_retry_after_s,
                   max(1.0, queue_depth / rate))


class Autoscaler:
    """Debounced scale-up/down decisions wired to the manager's hooks.

    Triggers:
    - ``note_queue_breach()`` — the shedding gate tripped (fleet queue
      over the SLO): scale up.
    - ``note_goodput_breach(ratio, slo, windows)`` — the lifecycle
      goodput-SLO alert (register via
      ``lifecycle.register_goodput_breach_hook``): scale up.
    - ``note_tick(queue_depth)`` — called each monitor sweep; after
      ``idle_ticks`` consecutive sweeps with an empty queue, scale
      down (the hook SIGTERM-drains one replica; never below
      ``min_replicas``).

    ``cooldown_s`` separates consecutive actions in either direction —
    a spawn takes seconds to warm, and reacting again before it lands
    just thrashes."""

    def __init__(self, scale_up=None, scale_down=None, min_replicas=1,
                 max_replicas=8, replica_count=None, cooldown_s=5.0,
                 idle_ticks=40, clock=time.monotonic):
        self._up = scale_up
        self._down = scale_down
        self._count = replica_count or (lambda: 0)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.idle_ticks = int(idle_ticks)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_action_t = None
        self._idle = 0
        self.actions: list = []       # (t, "up"/"down", reason) ring

    def _ready(self):
        return self._last_action_t is None or \
            self._clock() - self._last_action_t >= self.cooldown_s

    def _act(self, direction, reason, hook):
        with self._lock:
            if not self._ready():
                return False
            n = self._count()
            if direction == "up" and n >= self.max_replicas:
                return False
            if direction == "down" and n <= self.min_replicas:
                return False
            self._last_action_t = self._clock()
            self._idle = 0
            self.actions.append((self._last_action_t, direction, reason))
            del self.actions[:-64]
        if hook is not None:
            hook(reason)
        return True

    def note_queue_breach(self, depth=None):
        return self._act("up", f"queue SLO breach (depth {depth})",
                         self._up)

    def note_goodput_breach(self, ratio, slo, windows):
        return self._act(
            "up", f"goodput breach ({ratio:.3f} < {slo:.3f})", self._up)

    def note_tick(self, queue_depth):
        with self._lock:
            self._idle = self._idle + 1 if queue_depth == 0 else 0
            idle = self._idle
        if idle >= self.idle_ticks:
            return self._act("down", f"idle for {idle} sweeps",
                             self._down)
        return False
