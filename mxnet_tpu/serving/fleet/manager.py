"""Fleet manager: replica processes, warm replacement, and scaling.

The router (router.py) owns dispatch; this module owns the replica
*set*: spawning real engine processes, detecting-to-replacing dead
ones, and the scale-up/down hooks the :class:`policy.Autoscaler`
drives.  Every spawn goes through the ``fleet.spawn`` fault seam
(``call_with_retries`` — a tripped or genuinely failed spawn retries
under the shared policy instead of silently shrinking the fleet).

Two replica modes share the machinery:

- **process mode** (:class:`ProcessReplica`): the manager launches
  ``spawn_cmd(rid)``'s argv, waits for the engine's ready line on
  stdout, and talks HTTP through the transport funnel.  Warm
  replacement = every replica sharing one ``MXNET_COMPILE_CACHE_DIR``:
  the first replica pays the AOT compiles, every later spawn loads
  the persisted executables and serves its first token several times
  faster (the PR 13 warm-restart property, now a fleet recovery
  bound).
- **local mode** (``engine_factory``): in-process replicas for unit
  tests, bench, and embedders.  The factory receives a running donor
  engine (or None) — handing it to ``ServingEngine.join_replica``
  gets the live param-donation warm path.

:func:`serve_fleet` is the blocking entrypoint mirroring
``serving.serve``: router + N replicas + HTTP front door, SIGTERM
drains every replica and exits ``lifecycle.EXIT_PREEMPTED``.
"""
from __future__ import annotations

import logging
import os
import re
import signal
import subprocess
import sys
import threading
import time

from ... import env as _env
from ... import fault as _fault
from ... import telemetry as _telemetry
from ...base import MXNetError
from . import transport as _transport
from .router import ReplicaHandle, Router

__all__ = ["ProcessReplica", "FleetManager", "serve_fleet"]

_LOGGER = logging.getLogger(__name__)

_C_SPAWNS = _telemetry.counter(
    "mxnet_fleet_spawns_total",
    "replica spawns by kind (initial / replacement / scale_up)",
    labelnames=("kind",))
_H_SPAWN = _telemetry.histogram(
    "mxnet_fleet_spawn_seconds",
    "replica spawn → ready wall time (warm spawns load the shared "
    "compile cache and land far left of the cold first replica)")

# the engine's serve() ready banner IS the readiness protocol — one
# line, already printed, survives refactors that forget a side channel
_READY_RE = re.compile(r"engine up on 127\.0\.0\.1:(\d+)")


class ProcessReplica(ReplicaHandle):
    """A replica living in its own OS process, reached over HTTP
    through the transport funnel.  ``proc`` is the Popen handle (the
    liveness source: ``poll()`` catches a SIGKILL the instant the
    kernel reaps it, no probe timeout needed)."""

    def __init__(self, rid, proc, host, port, **kw):
        super().__init__(rid, **kw)
        self.proc = proc
        self.host = str(host)
        self.port = int(port)

    def alive(self):
        return self.proc.poll() is None

    def probe(self):
        return _transport.get_json(
            self.host, self.port, "/v1/serving",
            deadline=time.monotonic() + 1.0)

    def submit(self, freq, retries=0):
        payload = {
            "prompt": freq.prompt,
            "max_new_tokens": freq.max_new_tokens,
            "temperature": freq.temperature,
            "eos_id": freq.eos_id,
            "deadline_ms": max(1, int(freq.remaining_s() * 1e3)),
            "timeout_s": max(0.001, freq.remaining_s()),
            "trace_id": freq.id,
            "return_trace": True,
        }
        return _transport.post_json(
            self.host, self.port, "/v1/completions", payload,
            deadline=freq.deadline, retries=retries)

    def shutdown(self, drain=True, timeout=30):
        """Graceful stop: SIGTERM rides the replica's lifecycle drain
        (in-flight finishes, queued rejects cleanly); escalate to
        SIGKILL only past ``timeout``."""
        if self.proc.poll() is not None:
            return
        try:
            self.proc.send_signal(
                signal.SIGTERM if drain else signal.SIGKILL)
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _LOGGER.warning("replica %s ignored SIGTERM for %ss; "
                            "killing", self.rid, timeout)
            self.proc.kill()
            self.proc.wait(timeout=5)

    def kill(self):
        """The chaos path: immediate SIGKILL, no drain, no goodbye."""
        if self.proc.poll() is None:
            self.proc.kill()


class FleetManager:
    """Owns the replica set.  Exactly one of ``spawn_cmd`` (process
    mode: ``spawn_cmd(rid) -> (argv, extra_env)``) or
    ``engine_factory`` (local mode: ``engine_factory(rid, donor) ->
    started engine``) must be given."""

    def __init__(self, spawn_cmd=None, engine_factory=None,
                 replicas=None, max_replicas=8, auto_heal=True,
                 ready_timeout_s=180.0, eject_threshold=None,
                 probe_interval_ms=None):
        if (spawn_cmd is None) == (engine_factory is None):
            raise MXNetError("FleetManager needs exactly one of "
                             "spawn_cmd / engine_factory")
        self._spawn_cmd = spawn_cmd
        self._engine_factory = engine_factory
        self.target_replicas = int(replicas) if replicas is not None \
            else _env.fleet_replicas()
        self.max_replicas = int(max_replicas)
        self.auto_heal = bool(auto_heal)
        self.ready_timeout_s = float(ready_timeout_s)
        self._eject_threshold = eject_threshold if eject_threshold \
            is not None else _env.fleet_eject_threshold()
        self._probe_interval_s = (
            probe_interval_ms if probe_interval_ms is not None
            else _env.fleet_probe_interval_ms()) / 1e3
        self.router = None            # attached by attach_router
        self._seq = 0
        self._lock = threading.Lock()
        self._stopping = False
        self.spawn_times: list = []   # (rid, kind, ready_seconds)

    def attach_router(self, router):
        self.router = router
        router._manager = self
        return self

    def _next_rid(self):
        with self._lock:
            self._seq += 1
            return f"replica-{self._seq}"

    # -- spawning ----------------------------------------------------------
    def spawn_replica(self, kind="initial", donor=None):
        """Bring one replica up (through the ``fleet.spawn`` seam,
        transient spawn failures retried) and register it with the
        router.  Returns the new handle."""
        rid = self._next_rid()
        t0 = time.monotonic()
        handle = _fault.call_with_retries(
            "fleet.spawn", self._spawn_one, rid, donor)
        dt = time.monotonic() - t0
        _C_SPAWNS.labels(kind=kind).inc()
        _H_SPAWN.observe(dt)
        with self._lock:
            self.spawn_times.append((rid, kind, dt))
        if self.router is not None:
            self.router.add_replica(handle)
        _LOGGER.info("fleet: %s %s ready in %.2fs", kind, rid, dt)
        return handle

    def _spawn_one(self, rid, donor):
        if self._engine_factory is not None:
            from .router import LocalReplica

            engine = self._engine_factory(rid, donor)
            return LocalReplica(
                rid, engine, eject_threshold=self._eject_threshold,
                probe_interval_s=self._probe_interval_s)
        argv, extra_env = self._spawn_cmd(rid)
        env = dict(os.environ)
        env.update(extra_env or {})
        proc = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        port = self._wait_ready(rid, proc)
        return ProcessReplica(
            rid, proc, "127.0.0.1", port,
            eject_threshold=self._eject_threshold,
            probe_interval_s=self._probe_interval_s)

    def _wait_ready(self, rid, proc):
        """Block until the child prints the engine ready banner; a
        child that dies or stalls first is a failed spawn (OSError →
        transient → the seam's retry policy takes it)."""
        deadline = time.monotonic() + self.ready_timeout_s
        # readline on a pipe has no timeout; a reader thread + join
        # bounds it without platform-specific select dances
        result: dict = {}

        def read():
            for line in proc.stdout:
                m = _READY_RE.search(line)
                if m:
                    result["port"] = int(m.group(1))
                    break
            # keep draining so the child never blocks on a full pipe
            for _ in proc.stdout:
                pass

        t = threading.Thread(target=read, daemon=True,
                             name=f"mxnet-fleet-ready-{rid}")
        t.start()
        while "port" not in result:
            if proc.poll() is not None:
                raise OSError(f"replica {rid} exited "
                              f"{proc.returncode} before ready")
            if time.monotonic() > deadline:
                proc.kill()
                raise OSError(f"replica {rid} not ready within "
                              f"{self.ready_timeout_s}s")
            time.sleep(0.05)
        return result["port"]

    def ensure(self, n=None, donor=None):
        """Spawn until the router has ``n`` (default target) replicas."""
        n = self.target_replicas if n is None else int(n)
        out = []
        while len(self.router.replicas()) < n:
            kind = "initial" if not self.router.replicas() or donor \
                is None else "scale_up"
            out.append(self.spawn_replica(kind=kind, donor=donor))
        return out

    # -- failure recovery --------------------------------------------------
    def on_replica_dead(self, replica):
        """Router callback (after it resubmitted the in-flight work):
        drop the corpse from rotation and heal the fleet size with a
        warm replacement — asynchronously, spawning takes seconds and
        the dispatch plane must not wait on it."""
        if self.router is not None:
            self.router.remove_replica(replica)
        if not self.auto_heal or self._stopping:
            return

        def heal():
            try:
                donor = self._pick_donor()
                self.spawn_replica(kind="replacement", donor=donor)
            except Exception:
                _LOGGER.exception("fleet: replacement spawn failed")

        threading.Thread(target=heal, daemon=True,
                         name="mxnet-fleet-heal").start()

    def _pick_donor(self):
        """A healthy LocalReplica engine whose params can be donated
        (join_replica); process mode has no donor — its warmth is the
        shared compile cache."""
        from .health import HEALTHY
        from .router import LocalReplica

        if self.router is None:
            return None
        for r in self.router.replicas():
            if isinstance(r, LocalReplica) and r.alive() and \
                    r.health.state == HEALTHY:
                return r.engine
        return None

    # -- scaling (Autoscaler hooks) ----------------------------------------
    def scale_up(self, reason=""):
        if self._stopping or \
                len(self.router.replicas()) >= self.max_replicas:
            return None
        _LOGGER.info("fleet: scaling up (%s)", reason)
        return self.spawn_replica(kind="scale_up",
                                  donor=self._pick_donor())

    def scale_down(self, reason=""):
        """Retire ONE replica via the SIGTERM drain path: it finishes
        in-flight work, rejects queued work cleanly (the router holds
        the queue, so there is none replica-side), and exits."""
        reps = self.router.replicas()
        if self._stopping or len(reps) <= 1:
            return None
        # retire the least-loaded live replica
        victim = min(reps, key=lambda r: r.inflight_count())
        _LOGGER.info("fleet: scaling down %s (%s)", victim.rid, reason)
        self.router.remove_replica(victim)

        def drain():
            victim.shutdown(drain=True)

        threading.Thread(target=drain, daemon=True,
                         name="mxnet-fleet-drain").start()
        return victim

    def drain_all(self, timeout=60):
        """Fleet shutdown: SIGTERM-drain every replica in parallel."""
        self._stopping = True
        reps = self.router.replicas() if self.router is not None else []
        threads = []
        for r in reps:
            t = threading.Thread(target=r.shutdown,
                                 kwargs={"drain": True,
                                         "timeout": timeout},
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=timeout)
        for r in reps:
            if self.router is not None:
                self.router.remove_replica(r)


def serve_fleet(spawn_cmd=None, engine_factory=None, replicas=None,
                port=None, install_signals=True, on_ready=None,
                autoscale=False, **router_kw):
    """Blocking fleet entrypoint (the multi-replica analog of
    ``serving.serve``): spawn the replicas, start the router, mount
    the HTTP front door beside ``/metrics``, and run until a graceful
    stop.  SIGTERM drains every replica and returns
    ``lifecycle.EXIT_PREEMPTED``; ``on_ready(router, bound_port)``
    fires once the fleet is serving."""
    from ... import lifecycle
    from .policy import Autoscaler

    if install_signals:
        lifecycle.install_signal_handlers()
    server = _telemetry.start_http_server(
        port if port is not None else (_env.serving_port() or 0))
    manager = FleetManager(spawn_cmd=spawn_cmd,
                           engine_factory=engine_factory,
                           replicas=replicas)
    router = Router(**router_kw)
    manager.attach_router(router)
    scaler = None
    if autoscale:
        scaler = Autoscaler(
            scale_up=manager.scale_up, scale_down=manager.scale_down,
            max_replicas=manager.max_replicas,
            replica_count=lambda: len(router.replicas()))
        router._autoscaler = scaler
    manager.ensure()
    router.start()
    router.mount_http()
    bound = server.server_address[1]
    print(f"mxnet_tpu fleet: router up on 127.0.0.1:{bound} with "
          f"{len(router.replicas())} replicas (/v1/completions, "
          f"/v1/fleet, /metrics)", flush=True)
    if on_ready is not None:
        on_ready(router, bound)
    try:
        while not lifecycle.stop_requested():
            time.sleep(0.1)
    finally:
        manager.drain_all()
        router.close()
    lifecycle.cancel_grace_deadline()
    return lifecycle.EXIT_PREEMPTED if lifecycle.stop_requested() else 0


if __name__ == "__main__":   # pragma: no cover - manual entrypoint
    sys.exit(serve_fleet())
