"""Training callbacks (reference: python/mxnet/callback.py — Speedometer,
do_checkpoint, log_train_metric)."""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "ProgressBar"]


class Speedometer:
    """Logs samples/sec every `frequent` batches (async-aware: wall-clock
    between callback invocations, same as the reference).

    ``telemetry=True`` additionally publishes the measured speed to the
    runtime telemetry registry (``mxnet_speedometer_samples_per_sec``
    gauge + ``mxnet_speedometer_batches_total``) so throughput is
    scrapeable from a running job, not just greppable from logs."""

    def __init__(self, batch_size, frequent=50, auto_reset=True,
                 telemetry=False):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset
        self.telemetry = telemetry

    def _emit(self, speed):
        from . import telemetry as _tel

        _tel.gauge("mxnet_speedometer_samples_per_sec",
                   "throughput over the last Speedometer window").set(speed)
        _tel.counter("mxnet_speedometer_batches_total",
                     "batches seen by Speedometer").inc(self.frequent)

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if self.telemetry:
                    self._emit(speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    logging.info(msg, param.epoch, count, speed,
                                 "\t".join(f"{n}={v:f}" for n, v in name_value))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix, period=1):
    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            from .ndarray.serialization import save

            data = {}
            if arg:
                data.update({f"arg:{k}": v for k, v in arg.items()})
            if aux:
                data.update({f"aux:{k}": v for k, v in aux.items()})
            save(f"{prefix}-{iter_no + 1:04d}.params", data)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, count):
        import sys

        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = round(100.0 * count / float(self.total), 1)
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        sys.stdout.write(f"[{prog_bar}] {percents}%\r")
        sys.stdout.flush()
