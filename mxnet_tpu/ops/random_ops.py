"""Random sampling operators.

Reference: ``src/operator/random/*.{cc,cu}`` (sample_op, multisample_op,
shuffle_op — SURVEY.md §3.2 "Random").  Every op takes an explicit jax PRNG
key as its first array input (threaded by the frontend, see
``mxnet_tpu/random.py``); the samplers are jax.random draws that XLA fuses.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jr():
    from jax import random as jr

    return jr


def _jnp():
    import jax.numpy as jnp

    return jnp


def _dt(dtype):
    if dtype in (None, "None"):
        return _np.float32
    if dtype == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return _np.dtype(dtype)


@register("random_uniform", creation=True, needs_rng=True, differentiable=False,
          aliases=("uniform", "_sample_uniform"))
def random_uniform(key, low=0.0, high=1.0, shape=None, dtype="float32"):
    return _jr().uniform(key, tuple(shape), minval=low, maxval=high,
                         dtype=_dt(dtype))


@register("random_normal", creation=True, needs_rng=True, differentiable=False,
          aliases=("normal", "_sample_normal"))
def random_normal(key, loc=0.0, scale=1.0, shape=None, dtype="float32"):
    return _jr().normal(key, tuple(shape), dtype=_dt(dtype)) * scale + loc


@register("random_gamma", creation=True, needs_rng=True, differentiable=False,
          aliases=("gamma_sample",))
def random_gamma(key, alpha=1.0, beta=1.0, shape=None, dtype="float32"):
    return _jr().gamma(key, alpha, tuple(shape), dtype=_dt(dtype)) * beta


@register("random_exponential", creation=True, needs_rng=True, differentiable=False)
def random_exponential(key, lam=1.0, shape=None, dtype="float32"):
    return _jr().exponential(key, tuple(shape), dtype=_dt(dtype)) / lam


@register("random_poisson", creation=True, needs_rng=True, differentiable=False)
def random_poisson(key, lam=1.0, shape=None, dtype="float32"):
    return _jr().poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register("random_negative_binomial", creation=True, needs_rng=True,
          differentiable=False)
def random_negative_binomial(key, k=1, p=1.0, shape=None, dtype="float32"):
    jr = _jr()
    # NB(k,p) = Poisson(Gamma(k, (1-p)/p))
    g = jr.gamma(key, k, tuple(shape)) * (1 - p) / p
    k2 = jr.fold_in(key, 1)
    return jr.poisson(k2, g, tuple(shape)).astype(_dt(dtype))


@register("random_randint", creation=True, needs_rng=True, differentiable=False)
def random_randint(key, low=0, high=1, shape=None, dtype="int32"):
    return _jr().randint(key, tuple(shape), int(low), int(high)).astype(_dt(dtype))


@register("sample_multinomial", needs_rng=True, differentiable=False,
          aliases=("multinomial",))
def sample_multinomial(key, data, shape=1, get_prob=False, dtype="int32"):
    jr = _jr()
    jnp = _jnp()
    n = shape if isinstance(shape, int) else int(_np.prod(shape))
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        draws = jr.categorical(key, logits, shape=(n,))
        out = draws if isinstance(shape, int) and shape == 1 else draws.reshape(shape if not isinstance(shape, int) else (shape,))
    else:
        draws = jr.categorical(key, logits[:, None, :], axis=-1,
                               shape=(data.shape[0], n))
        out = draws.reshape((data.shape[0],) + ((shape,) if isinstance(shape, int) else tuple(shape)))
        if isinstance(shape, int) and shape == 1:
            out = out.reshape(data.shape[0])
    return out.astype(_dt(dtype))


@register("sample_uniform_like", needs_rng=True, differentiable=False,
          aliases=("uniform_like",))
def uniform_like(key, data, low=0.0, high=1.0):
    return _jr().uniform(key, data.shape, minval=low, maxval=high,
                         dtype=data.dtype)


@register("sample_normal_like", needs_rng=True, differentiable=False,
          aliases=("normal_like",))
def normal_like(key, data, loc=0.0, scale=1.0):
    return _jr().normal(key, data.shape, dtype=data.dtype) * scale + loc


@register("bernoulli", creation=True, needs_rng=True, differentiable=False)
def bernoulli(key, prob=0.5, shape=None, dtype="float32"):
    return _jr().bernoulli(key, prob, tuple(shape)).astype(_dt(dtype))


# ==========================================================================
# Probability-density ops (reference: src/operator/random/pdf_op.cc —
# _random_pdf_*).  sample has one trailing draw axis over broadcast param
# shapes; fully differentiable wrt sample AND parameters (the reference
# hand-codes those gradients; jax derives them from the closed forms).
# ==========================================================================
def _pdf_out(logp, is_log):
    jnp = _jnp()

    return logp if is_log else jnp.exp(logp)


def _plog(x):
    """log with -inf-safe gradient at the support boundary."""
    jnp = _jnp()

    return jnp.log(jnp.maximum(x, 1e-30))


@register("_random_pdf_uniform", aliases=("random_pdf_uniform",))
def pdf_uniform(sample, low, high, is_log=False):
    jnp = _jnp()

    lo, hi = low[..., None], high[..., None]
    inside = (sample >= lo) & (sample <= hi)
    logp = jnp.where(inside, -_plog(hi - lo), -jnp.inf)
    return _pdf_out(logp, is_log)


@register("_random_pdf_normal", aliases=("random_pdf_normal",))
def pdf_normal(sample, mu, sigma, is_log=False):
    jnp = _jnp()

    m, s = mu[..., None], sigma[..., None]
    logp = (-0.5 * ((sample - m) / s) ** 2 - _plog(s)
            - 0.5 * _np.log(2 * _np.pi))
    return _pdf_out(logp, is_log)


@register("_random_pdf_gamma", aliases=("random_pdf_gamma",))
def pdf_gamma(sample, alpha, beta, is_log=False):
    """alpha: shape, beta: rate (reference pdf_op.cc gamma parameterization:
    p(x) = beta^alpha x^(alpha-1) e^(-beta x) / Gamma(alpha))."""
    from jax.scipy.special import gammaln

    a, b = alpha[..., None], beta[..., None]
    logp = (a * _plog(b) + (a - 1) * _plog(sample) - b * sample
            - gammaln(a))
    return _pdf_out(logp, is_log)


@register("_random_pdf_exponential", aliases=("random_pdf_exponential",))
def pdf_exponential(sample, lam, is_log=False):
    lamb = lam[..., None]
    logp = _plog(lamb) - lamb * sample
    return _pdf_out(logp, is_log)


@register("_random_pdf_poisson", aliases=("random_pdf_poisson",))
def pdf_poisson(sample, lam, is_log=False):
    from jax.scipy.special import gammaln

    lamb = lam[..., None]
    logp = sample * _plog(lamb) - lamb - gammaln(sample + 1.0)
    return _pdf_out(logp, is_log)


@register("_random_pdf_negative_binomial",
          aliases=("random_pdf_negative_binomial",))
def pdf_negative_binomial(sample, k, p, is_log=False):
    """P(x) = C(x+k-1, x) p^k (1-p)^x (reference parameterization: k
    failures, success probability p)."""
    from jax.scipy.special import gammaln

    kk, pp = k[..., None], p[..., None]
    logp = (gammaln(sample + kk) - gammaln(sample + 1.0) - gammaln(kk)
            + kk * _plog(pp) + sample * _plog(1.0 - pp))
    return _pdf_out(logp, is_log)


@register("_random_pdf_generalized_negative_binomial",
          aliases=("random_pdf_generalized_negative_binomial",))
def pdf_generalized_negative_binomial(sample, mu, alpha, is_log=False):
    """Polya (gamma-Poisson mixture) pdf over mean mu and dispersion alpha
    (reference: PDF_GeneralizedNegativeBinomial)."""
    from jax.scipy.special import gammaln

    m, a = mu[..., None], alpha[..., None]
    r = 1.0 / a
    logp = (gammaln(sample + r) - gammaln(sample + 1.0) - gammaln(r)
            + r * _plog(r / (r + m)) + sample * _plog(m / (r + m)))
    return _pdf_out(logp, is_log)


@register("_random_pdf_dirichlet", aliases=("random_pdf_dirichlet",))
def pdf_dirichlet(sample, alpha, is_log=False):
    """sample (..., n, k) over alpha (..., k): the trailing draw axis is
    second-to-last, each draw a k-simplex point (reference pdf_op.cc)."""
    from jax.scipy.special import gammaln

    jnp = _jnp()
    a = alpha[..., None, :]
    logp = (jnp.sum((a - 1.0) * _plog(sample), axis=-1)
            + gammaln(jnp.sum(a, axis=-1))
            - jnp.sum(gammaln(a), axis=-1))
    return _pdf_out(logp, is_log)
