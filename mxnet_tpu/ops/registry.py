"""Single operator table driving the whole ``mx.nd.*`` surface.

Reference: the NNVM op registry (``nnvm::Op`` + attr maps, SURVEY.md §3.1)
plus the import-time Python codegen (``python/mxnet/ndarray/register.py``).
The reference registers ~1000 C++ kernels with FInferShape/FCompute/FGradient
attrs; here each op is ONE pure jax-traceable Python function — shape/type
inference is jax abstract evaluation, FCompute is the function itself (XLA
compiles it), FGradient is ``jax.vjp`` of it.  One table → generated python
functions + docs, preserving the self-describing-surface property (§6.6).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "OP_TABLE"]

OP_TABLE = {}


class OpDef:
    """One operator.

    Attributes
    ----------
    fn : callable(*arrays, **attrs) -> array | tuple(arrays)
        Pure, jax-traceable.  Array inputs positional, static attrs kwargs.
    nout : int | 'dynamic'
        Number of outputs (tuple length) — 'dynamic' inspects the result.
    creation : bool
        True for ops with no array inputs (zeros, arange, random samplers):
        they accept ``ctx=``/``dtype=`` kwargs at the frontend.
    needs_rng : bool
        Frontend threads a jax PRNG key as the first positional array.
    differentiable : bool
        False -> never recorded on the autograd tape (int outputs etc.).
    jit_safe : bool
        False -> the eager jit-cache fast path (ndarray/dispatch_cache.py)
        never compiles this op: its Python body is intentionally re-run per
        call (reads env/global state at call time, value-dependent host
        logic).  Trace *failures* are additionally caught at runtime and
        blocklisted, so this flag is for ops that trace fine but must not
        be frozen into an executable.
    """

    __slots__ = ("name", "fn", "nout", "creation", "needs_rng", "differentiable",
                 "aliases", "jit_safe")

    def __init__(self, name, fn, nout=1, creation=False, needs_rng=False,
                 differentiable=True, aliases=(), jit_safe=True):
        self.name = name
        self.fn = fn
        self.nout = nout
        self.creation = creation
        self.needs_rng = needs_rng
        self.differentiable = differentiable
        self.aliases = aliases
        self.jit_safe = jit_safe


def register(name=None, nout=1, creation=False, needs_rng=False,
             differentiable=True, aliases=(), jit_safe=True):
    """Decorator: register a pure function as an operator."""

    def _do(fn):
        opname = name or fn.__name__
        od = OpDef(opname, fn, nout=nout, creation=creation, needs_rng=needs_rng,
                   differentiable=differentiable, aliases=aliases,
                   jit_safe=jit_safe)
        if opname in OP_TABLE:
            raise MXNetError(f"duplicate op registration: {opname}")
        OP_TABLE[opname] = od
        for a in aliases:
            OP_TABLE[a] = od
        return fn

    return _do


def get_op(name):
    od = OP_TABLE.get(name)
    if od is None:
        raise MXNetError(f"unknown operator {name!r}")
    return od


def list_ops():
    return sorted(OP_TABLE)
