"""Image operator family (reference: ``src/operator/image/*.{cc,cu}`` —
the GPU-capable Gluon transform path, SURVEY.md §3.2).

TPU-native: resize is ``jax.image.resize`` (XLA gather/convolution lowering);
color jitters are elementwise chains XLA fuses; random ops thread PRNG keys
through the registry's needs_rng path.  Layout is CHW/NCHW-agnostic where the
reference is (ops take either HWC or NHWC like the reference's image ops).
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _hwc_axes(x):
    """(h_axis, w_axis, c_axis) for HWC or NHWC input."""
    if x.ndim == 3:
        return 0, 1, 2
    return 1, 2, 3


@register("image_to_tensor", aliases=("to_tensor",))
def image_to_tensor(x):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: image/totensor op)."""
    jnp = _jnp()
    y = x.astype(_np.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(y, (2, 0, 1))
    return jnp.transpose(y, (0, 3, 1, 2))


@register("image_normalize")
def image_normalize(x, mean=0.0, std=1.0):
    """CHW/NCHW normalize (reference: image/normalize op)."""
    jnp = _jnp()
    mean = jnp.asarray(mean, dtype=x.dtype)
    std = jnp.asarray(std, dtype=x.dtype)
    if mean.ndim == 1:
        shape = (-1,) + (1, 1)
        mean = mean.reshape(shape)
        std = std.reshape(shape)
        if x.ndim == 4:
            mean = mean[None]
            std = std[None]
    return (x - mean) / std


@register("image_resize", aliases=("resize",))
def image_resize(x, size=None, keep_ratio=False, interp=1):
    """HWC/NHWC resize (reference: image/resize.cc).  interp: 0 nearest,
    1 bilinear, 2 bicubic (maps to jax.image methods)."""
    import jax

    if isinstance(size, int):
        size = (size, size)  # (w, h) like the reference
    w, h = size
    method = {0: "nearest", 1: "bilinear", 2: "bicubic"}.get(interp, "bilinear")
    if x.ndim == 3:
        shape = (h, w, x.shape[2])
    else:
        shape = (x.shape[0], h, w, x.shape[3])
    return jax.image.resize(x.astype(_np.float32), shape, method=method).astype(x.dtype)


@register("image_crop", aliases=("crop",))
def image_crop(x, x0=0, y0=0, width=None, height=None):
    """Fixed crop of HWC/NHWC (reference: image/crop.cc)."""
    if x.ndim == 3:
        return x[y0:y0 + height, x0:x0 + width, :]
    return x[:, y0:y0 + height, x0:x0 + width, :]


@register("image_flip_left_right", aliases=("flip_left_right",))
def image_flip_left_right(x):
    jnp = _jnp()
    _, w_ax, _ = _hwc_axes(x)
    return jnp.flip(x, axis=w_ax)


@register("image_flip_top_bottom", aliases=("flip_top_bottom",))
def image_flip_top_bottom(x):
    jnp = _jnp()
    h_ax, _, _ = _hwc_axes(x)
    return jnp.flip(x, axis=h_ax)


@register("image_random_flip_left_right", aliases=("random_flip_left_right",),
          needs_rng=True)
def image_random_flip_left_right(key, x):
    import jax
    jnp = _jnp()
    _, w_ax, _ = _hwc_axes(x)
    return jnp.where(jax.random.bernoulli(key), jnp.flip(x, axis=w_ax), x)


@register("image_random_flip_top_bottom", aliases=("random_flip_top_bottom",),
          needs_rng=True)
def image_random_flip_top_bottom(key, x):
    import jax
    jnp = _jnp()
    h_ax, _, _ = _hwc_axes(x)
    return jnp.where(jax.random.bernoulli(key), jnp.flip(x, axis=h_ax), x)


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


def _grayscale(x, c_ax):
    jnp = _jnp()
    weights = jnp.asarray([0.299, 0.587, 0.114], dtype=_np.float32)
    shape = [1] * x.ndim
    shape[c_ax] = 3
    g = jnp.sum(x * weights.reshape(shape), axis=c_ax, keepdims=True)
    return jnp.broadcast_to(g, x.shape)


@register("image_random_brightness", aliases=("random_brightness",),
          needs_rng=True)
def image_random_brightness(key, x, min_factor=1.0, max_factor=1.0):
    import jax

    alpha = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return x * alpha


@register("image_random_contrast", aliases=("random_contrast",),
          needs_rng=True)
def image_random_contrast(key, x, min_factor=1.0, max_factor=1.0):
    import jax
    jnp = _jnp()

    alpha = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    gray_mean = jnp.mean(x)
    return _blend(x, jnp.full_like(x, gray_mean), alpha)


@register("image_random_saturation", aliases=("random_saturation",),
          needs_rng=True)
def image_random_saturation(key, x, min_factor=1.0, max_factor=1.0):
    import jax

    *_, c_ax = _hwc_axes(x)
    alpha = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return _blend(x, _grayscale(x, c_ax), alpha)


@register("image_random_hue", aliases=("random_hue",), needs_rng=True)
def image_random_hue(key, x, min_factor=1.0, max_factor=1.0):
    """Approximate hue jitter via the reference's YIQ rotation
    (src/operator/image/image_random-inl.h RandomHue)."""
    import jax
    jnp = _jnp()

    *_, c_ax = _hwc_axes(x)
    alpha = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    u = jnp.cos(alpha * _np.pi)
    w = jnp.sin(alpha * _np.pi)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], dtype=_np.float32)
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], dtype=_np.float32)
    zero = jnp.zeros(())
    rot = jnp.stack([jnp.asarray([1.0, 0.0, 0.0], dtype=_np.float32),
                     jnp.stack([zero, u, -w]),
                     jnp.stack([zero, w, u])])
    m = t_rgb @ rot @ t_yiq
    xm = jnp.moveaxis(x, c_ax, -1)
    y = xm @ m.T
    return jnp.moveaxis(y, -1, c_ax)


@register("image_random_lighting", aliases=("random_lighting",),
          needs_rng=True)
def image_random_lighting(key, x, alpha_std=0.05):
    """AlexNet-style PCA lighting noise (reference: RandomLighting)."""
    import jax
    jnp = _jnp()

    *_, c_ax = _hwc_axes(x)
    eigval = jnp.asarray([55.46, 4.794, 1.148], dtype=_np.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.814],
                          [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)
    alpha = jax.random.normal(key, (3,)) * alpha_std
    delta = eigvec @ (alpha * eigval)
    shape = [1] * x.ndim
    shape[c_ax] = 3
    return x + delta.reshape(shape)


@register("image_random_color_jitter", aliases=("random_color_jitter",),
          needs_rng=True)
def image_random_color_jitter(key, x, brightness=0.0, contrast=0.0,
                              saturation=0.0, hue=0.0):
    import jax

    k1, k2, k3, k4 = jax.random.split(key, 4)
    if brightness > 0:
        x = image_random_brightness(k1, x, 1 - brightness, 1 + brightness)
    if contrast > 0:
        x = image_random_contrast(k2, x, 1 - contrast, 1 + contrast)
    if saturation > 0:
        x = image_random_saturation(k3, x, 1 - saturation, 1 + saturation)
    if hue > 0:
        x = image_random_hue(k4, x, -hue, hue)
    return x
