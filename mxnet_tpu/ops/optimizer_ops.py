"""Fused optimizer update operators.

Reference: ``src/operator/optimizer_op.{cc,cu,-inl.h}`` (sgd_update,
sgd_mom_update, adam_update, … — SURVEY.md §3.2 "Optimizer update ops").
Each update is one pure jax function over (weight, grad, states…) returning
the new (weight, states…); XLA fuses the whole update into a single kernel,
which is what the reference's hand-fused CUDA kernels bought.  The Optimizer
frontend jits these per (shape, dtype) so repeated steps hit the cache.
"""
from __future__ import annotations

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _prep(grad, wd, weight, rescale_grad, clip_gradient):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    return weight - lr * g


@register("sgd_mom_update", differentiable=False, nout=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", differentiable=False, nout=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", differentiable=False, nout=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, t=1):
    jnp = _jnp()
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    # bias correction folded into lr by the frontend (reference does the same)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("adagrad_update", differentiable=False, nout=2)
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    jnp = _jnp()
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_hist = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_hist) + epsilon), new_hist


@register("adadelta_update", differentiable=False, nout=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    jnp = _jnp()
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


@register("rmsprop_update", differentiable=False, nout=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    jnp = _jnp()
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", differentiable=False, nout=3)
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    # signature note: arrays are (weight, grad, n, g, delta)
    jnp = _jnp()
    gr = _prep(grad, wd, weight, rescale_grad, clip_gradient if clip_gradient > 0 else None)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(gr)
    new_g = gamma1 * g_state + (1 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g


@register("ftrl_update", differentiable=False, nout=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("ftml_update", differentiable=False, nout=3)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    jnp = _jnp()
    g = _prep(grad, wd, weight, rescale_grad, clip_grad if clip_grad > 0 else None)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v  # note: returns (weight, d, v); z handled by frontend


@register("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", differentiable=False, nout=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.9, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("lamb_update_phase1", differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m_hat, v_hat = new_mean, new_var
    if bias_correction:
        m_hat = new_mean / (1 - beta1 ** t)
        v_hat = new_var / (1 - beta2 ** t)
    update = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * weight
    return update


@register("lamb_update_phase2", differentiable=False)
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    jnp = _jnp()
    r1v = jnp.where(r1 > 0, r1, jnp.ones_like(r1))
    r2v = jnp.where(r2 > 0, r2, jnp.ones_like(r2))
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1v / r2v, jnp.ones_like(r1))
    if lower_bound is not None and lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    return weight - lr * ratio * g_update


@register("multi_sgd_update", differentiable=False, nout="dynamic")
def multi_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    """Aggregated SGD over many params in one launch (reference:
    multi_sgd_update / MXNET_OPTIMIZER_AGGREGATION_SIZE).  arrays =
    [w0, g0, w1, g1, ...]."""
    outs = []
    for i in range(num_weights):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        outs.append(sgd_update(w, g, lr=lrs[i], wd=wds[i],
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient))
    return tuple(outs) if len(outs) > 1 else outs[0]
