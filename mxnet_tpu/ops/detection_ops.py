"""Detection operator family (reference: ``src/operator/contrib/*.{cc,cu}``
— ROIAlign/ROIPooling, bounding_box.cc (box_nms/box_iou), multibox_*.cc
(SSD), SURVEY.md §3.2 "Detection-era contrib ops").

TPU-native design: everything is FIXED-SHAPE.  The reference's NMS writes a
variable number of survivors; here suppressed entries are overwritten with -1
scores (exactly the reference's output convention!) so the output shape equals
the input shape and XLA never sees a dynamic dimension — the pad-to-bucket
discipline of SURVEY.md §6.7.  Sorting/selection use XLA's sort; ROIAlign's
bilinear sampling is a gather + weighted sum that the MXU/VPU pipeline well.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


# --------------------------------------------------------------------------
# box utilities
# --------------------------------------------------------------------------
def _box_area(boxes, fmt):
    jnp = _jnp()
    if fmt == "corner":
        w = jnp.maximum(boxes[..., 2] - boxes[..., 0], 0)
        h = jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)
    else:  # center
        w, h = boxes[..., 2], boxes[..., 3]
    return w * h


def _to_corner(boxes, fmt):
    jnp = _jnp()
    if fmt == "corner":
        return boxes
    x, y, w, h = (boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3])
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _pairwise_iou(a, b, fmt="corner"):
    """IOU matrix between (..., N, 4) and (..., M, 4)."""
    jnp = _jnp()
    a = _to_corner(a, fmt)
    b = _to_corner(b, fmt)
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = _box_area(a, "corner")[..., :, None]
    area_b = _box_area(b, "corner")[..., None, :]
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):
    """Reference: src/operator/contrib/bounding_box.cc box_iou."""
    return _pairwise_iou(lhs, rhs, format)


@register("_contrib_box_nms", aliases=("box_nms",), differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Fixed-shape NMS (reference: BoxNMS in bounding_box-inl.h).

    Input (..., N, K): each row [class_id?, score, x1,y1,x2,y2, ...].
    Output: same shape; suppressed/invalid rows have score (and id) = -1 —
    the reference's convention, which happens to be exactly what a TPU wants
    (no dynamic shapes).  Implemented as an O(N²) mask over the
    score-sorted IOU matrix; N is anchor-count scale (≤ few thousand).
    """
    import jax
    jnp = _jnp()

    def _single(x):
        scores = x[:, score_index]
        boxes = x[:, coord_start:coord_start + 4]
        valid = scores > valid_thresh
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        if topk > 0:
            keep_topk = jnp.arange(x.shape[0]) < topk
        else:
            keep_topk = jnp.ones(x.shape[0], dtype=bool)
        xs = x[order]
        boxes_s = boxes[order]
        valid_s = valid[order] & keep_topk
        iou = _pairwise_iou(boxes_s, boxes_s, in_format)
        if id_index >= 0 and not force_suppress:
            ids = xs[:, id_index]
            same_class = ids[:, None] == ids[None, :]
            iou = jnp.where(same_class, iou, 0.0)
        overlap = (iou > overlap_thresh) & valid_s[None, :]

        def body(i, keep):
            sup = overlap[i] & keep & (jnp.arange(keep.shape[0]) > i)
            return jnp.where(keep[i], keep & ~sup, keep)

        keep = jax.lax.fori_loop(0, x.shape[0], body, valid_s)
        neg = jnp.full_like(xs[:, score_index], -1.0)
        out = xs.at[:, score_index].set(jnp.where(keep, xs[:, score_index], neg))
        if id_index >= 0:
            out = out.at[:, id_index].set(
                jnp.where(keep, out[:, id_index], neg))
        return out

    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(_single)(flat)
    return out.reshape(data.shape)


@register("ROIPooling", aliases=("roi_pooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max-pool ROI crops (reference: src/operator/roi_pooling.cc).
    data (N,C,H,W); rois (R,5) rows [batch_idx, x1,y1,x2,y2]."""
    return _roi_pool_impl(data, rois, pooled_size, spatial_scale, "max")


@register("_contrib_ROIAlign", aliases=("ROIAlign", "roi_align"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False):
    """Bilinear ROI align (reference: src/operator/contrib/roi_align.cc)."""
    import jax
    jnp = _jnp()

    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    ph, pw = pooled_size
    n, c, h, w = data.shape
    sr = max(int(sample_ratio), 1)
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(_np.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - offset, \
            roi[2] * spatial_scale - offset, roi[3] * spatial_scale - offset, \
            roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (ph*sr, pw*sr) points
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * bin_w / sr
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")

        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(_np.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(_np.int32)
        y0i = y0.astype(_np.int32)
        x0i = x0.astype(_np.int32)
        ly = jnp.clip(yy - y0, 0, 1)
        lx = jnp.clip(xx - x0, 0, 1)
        img = data[bidx]                               # (C,H,W)
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
               + v10 * ly * (1 - lx) + v11 * ly * lx)   # (C, ph*sr, pw*sr)
        val = val.reshape(c, ph, sr, pw, sr)
        return val.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


def _roi_pool_impl(data, rois, pooled_size, spatial_scale, mode):
    import jax
    jnp = _jnp()

    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    ph, pw = pooled_size
    n, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(_np.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = data[bidx]
        ygrid = jnp.arange(h, dtype=_np.float32)
        xgrid = jnp.arange(w, dtype=_np.float32)

        outs = []
        for py in range(ph):
            for px in range(pw):
                ys = jnp.floor(y1 + py * rh / ph)
                ye = jnp.ceil(y1 + (py + 1) * rh / ph)
                xs = jnp.floor(x1 + px * rw / pw)
                xe = jnp.ceil(x1 + (px + 1) * rw / pw)
                mask = ((ygrid[:, None] >= ys) & (ygrid[:, None] < ye)
                        & (xgrid[None, :] >= xs) & (xgrid[None, :] < xe))
                masked = jnp.where(mask[None], img, -jnp.inf)
                v = masked.max(axis=(1, 2))
                outs.append(jnp.where(jnp.isfinite(v), v, 0.0))
        return jnp.stack(outs, axis=1).reshape(c, ph, pw)

    return jax.vmap(one_roi)(rois)


# --------------------------------------------------------------------------
# SSD MultiBox family (reference: src/operator/contrib/multibox_*.cc)
# --------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior", "multibox_prior"),
          differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (reference: multibox_prior.cc).  data (N,C,H,W) →
    (1, H*W*(S+R-1), 4) corner-format anchors."""
    jnp = _jnp()
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[1] if steps[1] > 0 else 1.0 / h
    step_x = steps[0] if steps[0] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[1]) * step_y
    cx = (jnp.arange(w) + offsets[0]) * step_x
    cyy, cxx = jnp.meshgrid(cy, cx, indexing="ij")
    # anchor list: (size, ratio) pairs — first size with all ratios, then
    # remaining sizes with first ratio (the reference's S+R-1 convention)
    whs = []
    for r in ratios:
        sr = _np.sqrt(r)
        whs.append((sizes[0] * sr, sizes[0] / sr))
    for s in sizes[1:]:
        sr = _np.sqrt(ratios[0])
        whs.append((s * sr, s / sr))
    anchors = []
    for aw, ah in whs:
        anchors.append(jnp.stack([cxx - aw / 2, cyy - ah / 2,
                                  cxx + aw / 2, cyy + ah / 2], axis=-1))
    out = jnp.stack(anchors, axis=2).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",
                                              "multibox_target"),
          differentiable=False, nout=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1, negative_mining_ratio=-1,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor→GT matching + box-target encoding (multibox_target.cc).
    anchor (1,A,4) corner; label (N,M,5) rows [cls, x1,y1,x2,y2] (cls<0 pad);
    cls_pred (N, num_cls+1, A) unused except for shape.
    Returns (box_target (N,A*4), box_mask (N,A*4), cls_target (N,A))."""
    import jax
    jnp = _jnp()

    anchors = anchor.reshape(-1, 4)
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-12)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-12)

    def one_sample(lbl, pred):
        gt_valid = lbl[:, 0] >= 0                       # (M,)
        iou = _pairwise_iou(anchors, lbl[:, 1:5], "corner")   # (A,M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)               # (A,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= overlap_threshold
        # force-match: each valid gt claims its best anchor; padded label
        # rows scatter to an out-of-range index (mode='drop') so they can
        # neither claim nor clobber a real match
        best_anchor = jnp.argmax(iou, axis=0)           # (M,)
        na = anchors.shape[0]
        safe_anchor = jnp.where(gt_valid, best_anchor, na)
        forced = jnp.zeros(na, dtype=bool)
        forced = forced.at[safe_anchor].set(True, mode="drop")
        gt_of_forced = jnp.zeros(na, dtype=_np.int32)
        gt_of_forced = gt_of_forced.at[safe_anchor].set(
            jnp.arange(lbl.shape[0], dtype=_np.int32), mode="drop")
        use_gt = jnp.where(forced, gt_of_forced, best_gt)
        matched = matched | forced
        g = lbl[use_gt]                                  # (A,5)
        gcx = (g[:, 1] + g[:, 3]) / 2
        gcy = (g[:, 2] + g[:, 4]) / 2
        gw = jnp.maximum(g[:, 3] - g[:, 1], 1e-12)
        gh = jnp.maximum(g[:, 4] - g[:, 2], 1e-12)
        tx = (gcx - acx) / aw / variances[0]
        ty = (gcy - acy) / ah / variances[1]
        tw = jnp.log(gw / aw) / variances[2]
        th = jnp.log(gh / ah) / variances[3]
        box_t = jnp.stack([tx, ty, tw, th], axis=-1)    # (A,4)
        mask = matched[:, None].astype(box_t.dtype)
        if negative_mining_ratio > 0:
            # hard negative mining (multibox_target.cc semantics): unmatched
            # anchors below the mining IoU threshold compete by their max
            # non-background confidence; the top num_matched*ratio stay
            # background, the rest (and high-IoU unmatched) become
            # ignore_label so SSD doesn't drown in easy negatives
            neg_cand = (~matched) & (best_iou < negative_mining_thresh)
            conf = jnp.max(pred[1:, :], axis=0)          # (A,)
            k = jnp.maximum(
                matched.sum().astype(jnp.float32) * negative_mining_ratio,
                float(minimum_negative_samples))
            score = jnp.where(neg_cand, conf, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.zeros(na, dtype=jnp.int32).at[order].set(
                jnp.arange(na, dtype=jnp.int32))
            keep_neg = neg_cand & (rank.astype(jnp.float32) < k)
            cls_t = jnp.where(matched, g[:, 0] + 1,
                              jnp.where(keep_neg, 0.0, float(ignore_label)))
        else:
            cls_t = jnp.where(matched, g[:, 0] + 1, 0.0)  # 0 = background
        return (box_t * mask).reshape(-1), \
            jnp.broadcast_to(mask, box_t.shape).reshape(-1), cls_t

    bt, bm, ct = jax.vmap(one_sample)(label, cls_pred)
    return bt, bm, ct


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",
                                                 "multibox_detection"),
          differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS to detections (multibox_detection.cc).
    cls_prob (N,CLS,A); loc_pred (N,A*4); anchor (1,A,4).
    Returns (N, A, 6) rows [cls_id, score, x1,y1,x2,y2], invalid = -1."""
    import jax
    jnp = _jnp()

    anchors = anchor.reshape(-1, 4)
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def one(cp, lp):
        loc = lp.reshape(-1, 4)
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.delete(cp, background_id, axis=0, assume_unique_indices=True) \
            if hasattr(jnp, "delete") else cp[1:]
        cls_id = jnp.argmax(fg, axis=0).astype(boxes.dtype)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1.0)
        score = jnp.where(keep, score, -1.0)
        det = jnp.concatenate([cls_id[:, None], score[:, None], boxes], axis=1)
        return det

    det = jax.vmap(one)(cls_prob, loc_pred)
    return box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          differentiable=False, nout=2)
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1):
    """Greedy bipartite matching (reference: bounding_box.cc
    BipartiteMatching).  data (..., N, M) scores.  Returns (row→col match,
    col→row match), -1 for unmatched."""
    import jax
    jnp = _jnp()

    def single(x):
        n, m = x.shape
        big = jnp.inf if is_ascend else -jnp.inf

        def body(_, state):
            xm, rmatch, cmatch = state
            flat = jnp.argmin(xm) if is_ascend else jnp.argmax(xm)
            i, j = flat // m, flat % m
            v = xm[i, j]
            ok = (v < threshold) if is_ascend else (v > threshold)
            rmatch = jnp.where(ok, rmatch.at[i].set(j.astype(_np.float32)),
                               rmatch)
            cmatch = jnp.where(ok, cmatch.at[j].set(i.astype(_np.float32)),
                               cmatch)
            xm = xm.at[i, :].set(big)
            xm = xm.at[:, j].set(big)
            return xm, rmatch, cmatch

        rounds = min(n, m) if topk <= 0 else min(topk, min(n, m))
        _, rmatch, cmatch = jax.lax.fori_loop(
            0, rounds, body, (x, -jnp.ones(n), -jnp.ones(m)))
        return rmatch, cmatch

    flat = data.reshape((-1,) + data.shape[-2:])
    r, c = jax.vmap(single)(flat)
    return (r.reshape(data.shape[:-2] + (data.shape[-2],)),
            c.reshape(data.shape[:-2] + (data.shape[-1],)))


# ==========================================================================
# RPN Proposal (reference: src/operator/contrib/proposal.cc — the two-stage
# detector region-proposal op).  TPU-first: fixed shapes end to end —
# anchors enumerated on a static grid, top-K via lax.top_k, suppression via
# the same O(N²) masked NMS as box_nms, output padded to rpn_post_nms_top_n.
# ==========================================================================
def _enum_anchors(feat_h, feat_w, stride, scales, ratios, base_size):
    jnp = _jnp()

    base = jnp.asarray([0, 0, base_size - 1.0, base_size - 1.0])
    cx = (base[0] + base[2]) * 0.5
    cy = (base[1] + base[3]) * 0.5
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    size = w * h
    anchors = []
    for r in ratios:
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            anchors.append(jnp.stack([cx - 0.5 * (ws * s - 1),
                                      cy - 0.5 * (hs * s - 1),
                                      cx + 0.5 * (ws * s - 1),
                                      cy + 0.5 * (hs * s - 1)]))
    A = jnp.stack(anchors)                                     # (A, 4)
    sx = jnp.arange(feat_w) * stride
    sy = jnp.arange(feat_h) * stride
    shift = jnp.stack(jnp.meshgrid(sx, sy, indexing="xy"), axis=-1)
    shift = jnp.concatenate([shift, shift], axis=-1)           # (h, w, 4)
    return (shift[:, :, None, :] + A[None, None]).reshape(-1, 4)


def _bbox_transform_inv(anchors, deltas):
    jnp = _jnp()

    w = anchors[:, 2] - anchors[:, 0] + 1.0
    h = anchors[:, 3] - anchors[:, 1] + 1.0
    cx = anchors[:, 0] + 0.5 * (w - 1)
    cy = anchors[:, 1] + 0.5 * (h - 1)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(dw) * w
    ph = jnp.exp(dh) * h
    return jnp.stack([pcx - 0.5 * (pw - 1), pcy - 0.5 * (ph - 1),
                      pcx + 0.5 * (pw - 1), pcy + 0.5 * (ph - 1)], axis=1)


@register("_contrib_Proposal", aliases=("Proposal", "proposal"),
          differentiable=False, nout="dynamic")
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposals: anchors + deltas -> clip -> min-size -> top-K -> NMS.

    cls_prob (N, 2A, h, w) [bg scores first A maps, fg last A],
    bbox_pred (N, 4A, h, w), im_info (N, 3) [height, width, scale].
    Output: (N * post_nms_top_n, 5) rows [batch_idx, x1, y1, x2, y2]
    (+ scores when output_score), padded with the top box like the
    reference."""
    import jax
    jnp = _jnp()

    n, a2, h, w = cls_prob.shape
    A = a2 // 2
    anchors = _enum_anchors(h, w, feature_stride, scales, ratios,
                            float(feature_stride))

    def one(scores_map, deltas_map, info):
        # fg scores: channels A..2A, layout (A,h,w) -> (h,w,A) -> flat
        fg = scores_map[A:].transpose(1, 2, 0).reshape(-1)
        deltas = deltas_map.reshape(A, 4, h, w).transpose(2, 3, 0, 1)
        deltas = deltas.reshape(-1, 4)
        boxes = _bbox_transform_inv(anchors, deltas)
        # clip to image
        im_h, im_w = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im_w - 1.0),
            jnp.clip(boxes[:, 1], 0, im_h - 1.0),
            jnp.clip(boxes[:, 2], 0, im_w - 1.0),
            jnp.clip(boxes[:, 3], 0, im_h - 1.0)], axis=1)
        # min-size filter (scaled like the reference)
        min_size = rpn_min_size * info[2]
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        fg = jnp.where((ws >= min_size) & (hs >= min_size), fg, -1.0)
        # pre-NMS top-K (static K)
        k = min(rpn_pre_nms_top_n, fg.shape[0])
        top_scores, top_idx = jax.lax.top_k(fg, k)
        top_boxes = boxes[top_idx]
        # greedy NMS bounded by post_nms_top_n picks: each step selects the
        # best remaining box and suppresses its >threshold-IOU neighbours —
        # O(K·post_n) compute, O(K) memory per image (the full box_nms op's
        # K×K IOU matrix would be ~140 MB per image at the 6000 default)
        ws_t = top_boxes[:, 2] - top_boxes[:, 0] + 1
        hs_t = top_boxes[:, 3] - top_boxes[:, 1] + 1
        areas = ws_t * hs_t
        n_out = min(rpn_post_nms_top_n, k)

        def nms_body(i, carry):
            live, out_idx, out_val = carry
            j = jnp.argmax(live)
            sj = live[j]
            out_idx = out_idx.at[i].set(j)
            out_val = out_val.at[i].set(sj)
            bj = top_boxes[j]
            ix1 = jnp.maximum(top_boxes[:, 0], bj[0])
            iy1 = jnp.maximum(top_boxes[:, 1], bj[1])
            ix2 = jnp.minimum(top_boxes[:, 2], bj[2])
            iy2 = jnp.minimum(top_boxes[:, 3], bj[3])
            inter = (jnp.maximum(ix2 - ix1 + 1, 0.0)
                     * jnp.maximum(iy2 - iy1 + 1, 0.0))
            iou = inter / (areas + areas[j] - inter)
            live = jnp.where(iou > threshold, -jnp.inf, live)
            # threshold >= 1 ('NMS off') must still retire the picked box
            live = live.at[j].set(-jnp.inf)
            return live, out_idx, out_val

        _, keep_idx, keep_scores = jax.lax.fori_loop(
            0, n_out, nms_body,
            (top_scores, jnp.zeros((n_out,), "int32"), jnp.zeros((n_out,))))
        kept_boxes = top_boxes[keep_idx]
        # pad suppressed slots with the best box (reference pads output)
        best = kept_boxes[0]
        valid = keep_scores > 0
        out_boxes = jnp.where(valid[:, None], kept_boxes, best)
        out_scores = jnp.where(valid, keep_scores, 0.0)
        pad = rpn_post_nms_top_n - n_out
        if pad > 0:
            out_boxes = jnp.concatenate(
                [out_boxes, jnp.tile(best, (pad, 1))])
            out_scores = jnp.concatenate([out_scores, jnp.zeros(pad)])
        return out_boxes, out_scores

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(n, dtype=boxes.dtype),
                           rpn_post_nms_top_n)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


# ==========================================================================
# DeformableConvolution (reference: src/operator/contrib/
# deformable_convolution.cc — DCNv1).  TPU-first: the offset sampling is a
# dense bilinear gather (pure jnp, fuses fine), the contraction is one
# einsum onto the MXU; no im2col scratch in HBM beyond what XLA schedules.
# ==========================================================================
@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution", "deformable_convolution"))
def deformable_convolution(data, offset, weight, *maybe_bias, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=None, num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=None, layout=None):
    """data (N,C,H,W); offset (N, 2*dg*kh*kw, oh, ow) [dy,dx interleaved
    per tap]; weight (O, C/g, kh, kw)."""
    import jax
    jnp = _jnp()

    n, c, hh, ww = data.shape
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    oh = (hh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (ww + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group
    cg = c // dg

    ys = jnp.arange(oh) * sh - ph
    xs = jnp.arange(ow) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = ky[:, None] + ys[None, :]                   # (kh, oh)
    base_x = kx[:, None] + xs[None, :]                   # (kw, ow)

    def bilinear(img, y, x):
        """img (C', H, W); y/x (...) fractional coords -> (C', ...)"""
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def at(yy, xx):
            inb = (yy >= 0) & (yy < hh) & (xx >= 0) & (xx < ww)
            yy = jnp.clip(yy, 0, hh - 1).astype(jnp.int32)
            xx = jnp.clip(xx, 0, ww - 1).astype(jnp.int32)
            v = img[:, yy, xx]
            return jnp.where(inb[None], v, 0.0)

        return (at(y0, x0) * (1 - wy) * (1 - wx) +
                at(y0 + 1, x0) * wy * (1 - wx) +
                at(y0, x0 + 1) * (1 - wy) * wx +
                at(y0 + 1, x0 + 1) * wy * wx)

    def one(img, off):
        # off (2*dg*kh*kw, oh, ow) -> (dg, kh, kw, 2, oh, ow)
        off = off.reshape(dg, kh, kw, 2, oh, ow)
        cols = []
        for g in range(dg):
            oy = off[g, :, :, 0]                         # (kh, kw, oh, ow)
            ox = off[g, :, :, 1]
            y = base_y[:, None, :, None] + oy            # (kh, kw, oh, ow)
            x = base_x[None, :, None, :] + ox
            sampled = bilinear(img[g * cg:(g + 1) * cg], y, x)
            cols.append(sampled)                         # (cg, kh, kw, oh, ow)
        return jnp.concatenate(cols, axis=0)             # (C, kh,kw,oh,ow)

    cols = jax.vmap(one)(data, offset)                   # (N, C, kh,kw,oh,ow)
    cpg = c // num_group
    opg = num_filter // num_group
    cols_g = cols.reshape(n, num_group, cpg, kh, kw, oh, ow)
    w_g = weight.reshape(num_group, opg, cpg, kh, kw)
    out = jnp.einsum("ngcklyx,gockl->ngoyx", cols_g, w_g,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, num_filter, oh, ow).astype(data.dtype)
    if maybe_bias and not no_bias:
        out = out + maybe_bias[0].reshape(1, -1, 1, 1)
    return out


# ==========================================================================
# PSROIPooling (reference: src/operator/contrib/psroi_pooling.cc — R-FCN's
# position-sensitive pooling).  TPU-first: fixed-size sampled average per
# bin (the ROIAlign-style regular grid), channels split into pooled_size²
# position groups.
# ==========================================================================
@register("_contrib_PSROIPooling", aliases=("PSROIPooling", "psroi_pooling"))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                  pooled_size=7, group_size=None, sample_per_part=2):
    """data (N, output_dim*g*g, H, W); rois (R, 5) [batch, x1,y1,x2,y2].
    Output (R, output_dim, g, g) with bin (i,j) read from channel group
    (i*g+j)."""
    import jax
    jnp = _jnp()

    g = group_size or pooled_size
    n, ctot, hh, ww = data.shape
    if output_dim is not None and ctot != output_dim * g * g:
        raise ValueError(
            f"PSROIPooling: {ctot} channels != output_dim*group_size² "
            f"({output_dim}*{g}²={output_dim * g * g})")
    s = sample_per_part

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw = rw / g
        bh = rh / g
        img = jnp.take(data, b, axis=0)                  # (C, H, W)

        iy = jnp.arange(g)
        ix = jnp.arange(g)
        sy = (jnp.arange(s) + 0.5) / s
        sx = (jnp.arange(s) + 0.5) / s
        # sample points per bin: (g, s) coords each axis
        yy = y1 + (iy[:, None] + sy[None, :]) * bh       # (g, s)
        xx = x1 + (ix[:, None] + sx[None, :]) * bw
        yy = jnp.clip(yy, 0, hh - 1)
        xx = jnp.clip(xx, 0, ww - 1)

        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        wy = yy - y0
        wx = xx - x0
        y1i = jnp.minimum(y0 + 1, hh - 1)
        x1i = jnp.minimum(x0 + 1, ww - 1)

        cmap = data.shape[1] // (g * g)                  # = output_dim
        chan = (iy[:, None] * g + ix[None, :])           # (g, g) group idx
        # channel index per (dim, gy, gx): dim*g*g + group
        dims = jnp.arange(cmap)
        ch = dims[:, None, None] * g * g + chan[None]    # (dim, g, g)

        def gather(yi, xi):
            # (dim,g,g) channels x (g,s) y x (g,s) x -> (dim,g,g,s,s)
            return img[ch[:, :, :, None, None],
                       yi[None, :, None, :, None],
                       xi[None, None, :, None, :]]

        # four-corner bilinear; wy (g,s) indexed by (gy,sy), wx by (gx,sx)
        wy_b = wy[:, None, :, None]                      # (g,1,s,1)
        wx_b = wx[None, :, None, :]                      # (1,g,1,s)
        v00 = gather(y0, x0)
        v10 = gather(y1i, x0)
        v01 = gather(y0, x1i)
        v11 = gather(y1i, x1i)
        out = (v00 * ((1 - wy_b) * (1 - wx_b))[None] +
               v10 * (wy_b * (1 - wx_b))[None] +
               v01 * ((1 - wy_b) * wx_b)[None] +
               v11 * (wy_b * wx_b)[None])
        return out.mean(axis=(3, 4))                     # (dim, g, g)

    return jax.vmap(one_roi)(rois).astype(data.dtype)
