"""INT8 quantization operators.

Reference: ``src/operator/quantization/{quantize,quantize_v2,dequantize,
requantize}-inl.h`` and the quantized conv/FC kernels (SURVEY.md §3.2
quantization row).  TPU-native design: symmetric int8 with power-free
scales, int8 x int8 -> int32 matmuls through ``lax.dot_general``/
``conv_general_dilated`` with ``preferred_element_type=int32`` (XLA maps
these onto the MXU's native int8 path), and scale/bias epilogues left to
XLA fusion instead of hand-fused kernels.
"""
from __future__ import annotations

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _symmetric_scale(min_range, max_range, qmax=127.0):
    jnp = _jnp()

    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return jnp.maximum(amax, 1e-12) / qmax


@register("_contrib_quantize_v2", nout=3, differentiable=False,
          aliases=("quantize_v2",))
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """fp32 -> int8 given a calibrated range (reference: quantize_v2).

    Symmetric: scale = max(|min|,|max|)/127, q = round(x/scale) clipped.
    Returns (quantized, min_range, max_range) like the reference."""
    jnp = _jnp()

    if min_calib_range is None or max_calib_range is None:
        lo = jnp.min(data)
        hi = jnp.max(data)
    else:
        lo = jnp.float32(min_calib_range)
        hi = jnp.float32(max_calib_range)
    scale = _symmetric_scale(lo, hi)
    q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
    amax = scale * 127.0
    return q, -amax, amax


@register("_contrib_quantize", nout=3, differentiable=False,
          aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="int8"):
    """fp32 -> int8 with the range provided as arrays (reference:
    quantize)."""
    jnp = _jnp()

    scale = _symmetric_scale(jnp.min(min_range), jnp.max(max_range))
    q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
    amax = scale * 127.0
    return q, -amax, amax


@register("_contrib_dequantize", differentiable=False,
          aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    jnp = _jnp()

    scale = _symmetric_scale(jnp.min(min_range), jnp.max(max_range))
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize", nout=3, differentiable=False,
          aliases=("requantize",))
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 (reference: requantize).  The int32 range
    is min/max_range; the target int8 range comes from calibration (or the
    actual data range when uncalibrated)."""
    jnp = _jnp()

    in_scale = _symmetric_scale(jnp.min(min_range), jnp.max(max_range),
                                qmax=2147483647.0)
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.float32(min_calib_range)
        hi = jnp.float32(max_calib_range)
    else:
        lo = jnp.min(real)
        hi = jnp.max(real)
    out_scale = _symmetric_scale(lo, hi)
    q = jnp.clip(jnp.round(real / out_scale), -127, 127).astype(jnp.int8)
    amax = out_scale * 127.0
    return q, -amax, amax


@register("_contrib_quantized_fully_connected", differentiable=False,
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(x, weight_q, wscale, act_range, *maybe_bias,
                              num_hidden=None, no_bias=False, flatten=True):
    """Fused int8 dense: quantize activation (calibrated range) -> int8
    matmul with int32 accumulation on the MXU -> fp32 rescale (+ bias).

    weight_q int8 (units, in); wscale fp32 per-output-channel (units,);
    act_range fp32 (2,) = calibrated [min, max] (an array input so
    quantized models serialize it with their parameters).
    Reference: quantized_fully_connected-inl.h (per-tensor); per-channel
    weight scales are the TPU upgrade (free in the XLA epilogue)."""
    import jax
    jnp = _jnp()

    x2 = x.reshape(x.shape[0], -1) if flatten else x
    ascale = _symmetric_scale(act_range[0], act_range[1])
    xq = jnp.clip(jnp.round(x2 / ascale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, weight_q, (((x2.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (ascale * wscale)
    if maybe_bias and not no_bias:
        y = y + maybe_bias[0]
    return y


@register("_contrib_quantized_conv", differentiable=False,
          aliases=("quantized_conv",))
def quantized_conv(x, weight_q, wscale, act_range, *maybe_bias, kernel=None,
                   stride=None, pad=None, dilate=None, num_filter=None,
                   num_group=1, no_bias=False, layout=None):
    """Fused int8 NCHW convolution with int32 MXU accumulation.

    weight_q int8 (O, I/g, kh, kw); wscale fp32 (O,); act_range fp32 (2,)
    = calibrated [min, max]."""
    import jax
    from jax import lax
    jnp = _jnp()

    if layout not in (None, "NCHW"):
        from ..base import MXNetError

        raise MXNetError(
            f"quantized_conv lowers NCHW only, got layout={layout!r}")
    nd = x.ndim - 2
    strides = tuple(stride) if stride else (1,) * nd
    dil = tuple(dilate) if dilate else (1,) * nd
    pads = [(p, p) for p in (tuple(pad) if pad else (0,) * nd)]
    ascale = _symmetric_scale(act_range[0], act_range[1])
    xq = jnp.clip(jnp.round(x / ascale), -127, 127).astype(jnp.int8)
    dn = lax.conv_dimension_numbers(x.shape, weight_q.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        xq, weight_q, window_strides=strides, padding=pads,
        rhs_dilation=dil, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    scale = (ascale * wscale).reshape((1, -1) + (1,) * nd)
    y = acc.astype(jnp.float32) * scale
    if maybe_bias and not no_bias:
        y = y + maybe_bias[0].reshape((1, -1) + (1,) * nd)
    return y
