"""Legacy standalone ops: Correlation and SVMOutput.

Reference: ``src/operator/correlation.cc`` (the FlowNet correlation layer)
and ``src/operator/svm_output.cc`` (SURVEY.md §3.2 legacy rows).

TPU-first: Correlation is expressed as a displacement-stacked elementwise
product + box reduce_window — dense, static-shaped, fully XLA-fusable (the
CUDA original hand-tiles shared memory; the MXU/VPU path needs none of
that).  SVMOutput pins its loss gradient with jax.custom_vjp exactly like
SoftmaxOutput.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


@register("Correlation", aliases=("correlation",))
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation (reference: correlation.cc CorrelationForward).

    data1/data2 (N, C, H, W) -> (N, D*D, H_out, W_out) with
    D = 2*floor(max_displacement/stride2)+1; each output channel is the
    patch correlation (or abs-difference) between data1 and data2 shifted
    by one displacement, averaged over kernel patch and channels."""
    import jax

    jnp = _jnp()
    k = int(kernel_size)
    if k % 2 == 0:
        # the reference's kernel_radius = (k-1)/2 centering math (and this
        # box-sum lowering) is only well-defined for odd patches
        raise ValueError("Correlation requires an odd kernel_size")
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    pad = int(pad_size)

    n, c, h, w = data1.shape
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    kr = (k - 1) // 2                      # kernel radius
    border = md + kr
    out_h = int(_np.ceil((ph - border * 2) / float(s1)))
    out_w = int(_np.ceil((pw - border * 2) / float(s1)))
    if out_h < 1 or out_w < 1:
        raise ValueError("Correlation: output would be empty; grow "
                         "pad_size or shrink max_displacement")
    grid = md // s2
    shifts = [(dy, dx) for dy in range(-grid * s2, grid * s2 + 1, s2)
              for dx in range(-grid * s2, grid * s2 + 1, s2)]
    sumelems = float(k * k * c)

    def one_shift(shift):
        dy, dx = shift
        shifted = jnp.roll(d2, (-dy, -dx), axis=(2, 3))
        prod = d1 * shifted if is_multiply else -jnp.abs(d1 - shifted)
        red = jnp.sum(prod, axis=1)                      # (N, ph, pw)
        # box-sum over the kernel patch
        win = jax.lax.reduce_window(
            red, 0.0, jax.lax.add, (1, k, k), (1, 1, 1),
            [(0, 0), (kr, kr), (kr, kr)])
        # sample output positions: start at border, step stride1
        ys = border + s1 * jnp.arange(out_h)
        xs = border + s1 * jnp.arange(out_w)
        return win[:, ys][:, :, xs] / sumelems           # (N, oh, ow)

    maps = [one_shift(sh) for sh in shifts]
    return jnp.stack(maps, axis=1)                       # (N, D*D, oh, ow)


@register("SVMOutput", aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Forward = identity on data; backward = the multiclass hinge-loss
    gradient, *ignoring* the incoming out_grad — a loss layer exactly like
    the reference (src/operator/svm_output.cc L1/L2-SVM kernels)."""
    import jax

    jnp = _jnp()
    margin = float(margin)
    reg = float(regularization_coefficient)

    @jax.custom_vjp
    def _svm(d, l):
        return d

    def _fwd(d, l):
        return d, (d, l)

    def _bwd(res, g):
        d, l = res
        li = l.astype(_np.int32)
        ncls = d.shape[-1]
        onehot = jax.nn.one_hot(li, ncls, dtype=d.dtype)
        score_y = jnp.take_along_axis(d, li[..., None], axis=-1)
        viol = margin - (score_y - d)      # margin violation per class
        if use_linear:                     # L1-SVM: subgradient of hinge
            mask = (viol > 0).astype(d.dtype) * (1.0 - onehot)
            grad = reg * (mask - onehot * jnp.sum(mask, axis=-1,
                                                  keepdims=True))
        else:                              # L2-SVM: grad of squared hinge
            act = jnp.maximum(viol, 0.0) * (1.0 - onehot)
            grad = 2.0 * reg * (act - onehot * jnp.sum(act, axis=-1,
                                                       keepdims=True))
        return grad, jnp.zeros_like(l)

    _svm.defvjp(_fwd, _bwd)
    return _svm(data, label)
