"""Transformer/NLP operator family.

Reference: ``src/operator/contrib/transformer.cc`` (1.6 interleaved-matmul
self-attention ops — a fusion, not a parallelism strategy, SURVEY.md §3.2)
plus net-new LLM ops (RMSNorm, RoPE) required by the BASELINE Llama config.
All pure jax; the fused-attention hot path is ops/flash_attention.py.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


# --------------------------------------------------------------------------
# interleaved-matmul attention ops (reference: transformer.cc).  Layout:
# qkv (L, B, 3*H*D) interleaved per head — the reference's memory layout.
# --------------------------------------------------------------------------
def _split_interleaved(qkv, heads, n):
    jnp = _jnp()
    L, B, E = qkv.shape
    d = E // (n * heads)
    x = qkv.reshape(L, B, heads, n, d)
    return [x[:, :, :, i, :] for i in range(n)]


@register("_contrib_interleaved_matmul_selfatt_qk",
          aliases=("interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(qkv, heads=1):
    """(L,B,3HD) -> scores (B*H, L, L), scaled by 1/sqrt(d)."""
    jnp = _jnp()
    q, k, _ = _split_interleaved(qkv, heads, 3)
    L, B, H, d = q.shape
    qt = q.transpose(1, 2, 0, 3).reshape(B * H, L, d)
    kt = k.transpose(1, 2, 0, 3).reshape(B * H, L, d)
    return jnp.einsum("xld,xmd->xlm", qt, kt) / _np.sqrt(d)


@register("_contrib_interleaved_matmul_selfatt_valatt",
          aliases=("interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(qkv, att, heads=1):
    """att (B*H,L,L) x V from qkv -> (L,B,H*D)."""
    jnp = _jnp()
    _, _, v = _split_interleaved(qkv, heads, 3)
    L, B, H, d = v.shape
    vt = v.transpose(1, 2, 0, 3).reshape(B * H, L, d)
    out = jnp.einsum("xlm,xmd->xld", att, vt)
    return out.reshape(B, H, L, d).transpose(2, 0, 1, 3).reshape(L, B, H * d)


@register("_contrib_interleaved_matmul_encdec_qk",
          aliases=("interleaved_matmul_encdec_qk",))
def interleaved_matmul_encdec_qk(q, kv, heads=1):
    jnp = _jnp()
    Lq, B, E = q.shape
    d = E // heads
    k, _ = _split_interleaved(kv, heads, 2)
    Lk = k.shape[0]
    qt = q.reshape(Lq, B, heads, d).transpose(1, 2, 0, 3).reshape(
        B * heads, Lq, d)
    kt = k.transpose(1, 2, 0, 3).reshape(B * heads, Lk, d)
    return jnp.einsum("xld,xmd->xlm", qt, kt) / _np.sqrt(d)


@register("_contrib_interleaved_matmul_encdec_valatt",
          aliases=("interleaved_matmul_encdec_valatt",))
def interleaved_matmul_encdec_valatt(kv, att, heads=1):
    jnp = _jnp()
    _, v = _split_interleaved(kv, heads, 2)
    Lk, B, H, d = v.shape
    Lq = att.shape[1]
    vt = v.transpose(1, 2, 0, 3).reshape(B * H, Lk, d)
    out = jnp.einsum("xlm,xmd->xld", att, vt)
    return out.reshape(B, H, Lq, d).transpose(2, 0, 1, 3).reshape(Lq, B, H * d)


# --------------------------------------------------------------------------
# LLM building-block ops (net-new capability, BASELINE config #5)
# --------------------------------------------------------------------------
@register("rms_norm")
def rms_norm(x, gamma, eps=1e-6):
    """RMSNorm (Llama-family normalization) — fp32 accumulation."""
    jnp = _jnp()
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    from jax import lax

    y = xf * lax.rsqrt(ms + eps)
    return (y * gamma.astype(jnp.float32)).astype(x.dtype)


@register("rope")
def rope(x, positions=None, base=10000.0, scale=1.0):
    """Rotary position embedding over the last dim.

    x (B, H, L, D) with D even; positions (L,) or (B, L) (defaults to
    arange).  Half-split convention (Llama)."""
    jnp = _jnp()
    b, h, l, d = x.shape
    if positions is None:
        positions = jnp.arange(l)
    positions = jnp.asarray(positions) * scale
    freqs = base ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[..., None] * freqs                  # (..., L, d/2)
    if angles.ndim == 2:        # (L, d/2): shared across batch and heads
        angles = angles[None, None]
    elif angles.ndim == 3:      # (B, L, d/2): per-batch, broadcast over heads
        angles = angles[:, None]
    cos = jnp.cos(angles).astype(x.dtype)
    sin = jnp.sin(angles).astype(x.dtype)
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1)


@register("swiglu")
def swiglu(gate, up):
    """SwiGLU gate: silu(gate) * up (Llama MLP)."""
    from jax import nn

    return nn.silu(gate) * up


@register("_contrib_moe_swiglu", aliases=("moe_swiglu",))
def moe_swiglu(x, router_weight, gate_proj, up_proj, down_proj,
               capacity_factor=1.25, aux_loss_weight=0.0):
    """Switch-MoE SwiGLU FFN over stacked expert weights (Mixtral-style;
    net-new vs the reference).  Registered as a first-class op so MoE
    models trace to Symbol and export/SymbolBlock-import like any other
    graph (fused RNN set the precedent for stateful library ops).

    x (B, L, H); router (H, E); gate/up (E, H, I); down (E, I, H).
    The aux load-balance loss rides the backward pass via inject_aux_loss
    when aux_loss_weight > 0 (Switch Transformer eq. 4)."""
    from ..parallel.expert_parallel import inject_aux_loss, moe_apply

    capacity_factor = float(capacity_factor)
    aux_loss_weight = float(aux_loss_weight)

    def expert_fn(p, toks):
        from jax import nn

        return (nn.silu(toks @ p["g"]) * (toks @ p["u"])) @ p["d"]

    b, l, h = x.shape
    toks = x.reshape(-1, h)
    out, aux = moe_apply(
        expert_fn, {"g": gate_proj, "u": up_proj, "d": down_proj},
        router_weight, toks, capacity_factor=capacity_factor)
    out = out.reshape(b, l, h)
    if aux_loss_weight:
        # router balance term rides the backward pass; without it routing
        # collapses onto few experts
        out = inject_aux_loss(
            out, aux_loss_weight
            * aux["load_balance_loss"].astype(out.dtype))
    return out
