"""Neural-net operator family.

Reference: ``src/operator/nn/*.{cc,cu,h}`` (+ cuDNN/MKLDNN variants, ~60k LoC
— SURVEY.md §3.2 "Dense NN ops").  TPU-native: convolutions and matmuls lower
via ``lax.conv_general_dilated`` / ``dot_general`` straight onto the MXU; the
cuDNN-autotune/MKLDNN-layout machinery has no analog because XLA's layout
assignment owns that decision.  API keeps MXNet's NCHW default layout; XLA
relayouts internally for the TPU.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    from jax import lax

    return lax


def _nn():
    from jax import nn

    return nn


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    return t if len(t) == n else t + t[-1:] * (n - len(t))


# ==========================================================================
# FullyConnected (reference: src/operator/nn/fully_connected.cc)
# ==========================================================================
@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(x, weight, *maybe_bias, num_hidden=None, no_bias=False,
                    flatten=True):
    jnp = _jnp()
    if flatten:
        x2 = x.reshape((x.shape[0], -1))
    else:
        x2 = x
    # weight layout: (num_hidden, in_units) — matches reference
    y = jnp.matmul(x2, weight.T)
    if not no_bias and maybe_bias:
        y = y + maybe_bias[0]
    return y


# ==========================================================================
# Convolution / Deconvolution (reference: src/operator/nn/convolution.cc)
# ==========================================================================
def _conv_dimnums(ndim, layout):
    """Channel-last weights use MXNet's NHWC kernel convention
    (num_filter, *spatial, C/group) — OHWI-style dimension numbers."""
    if ndim == 3:
        if layout == "NWC":
            return ("NHC", "OHI", "NHC")
        return ("NCH", "OIH", "NCH")
    if ndim == 4:
        if layout == "NHWC":
            return ("NHWC", "OHWI", "NHWC")
        return ("NCHW", "OIHW", "NCHW")
    if ndim == 5:
        if layout == "NDHWC":
            return ("NDHWC", "ODHWI", "NDHWC")
        return ("NCDHW", "OIDHW", "NCDHW")
    raise ValueError(f"conv input ndim {ndim} unsupported")


@register("Convolution", aliases=("convolution",))
def convolution(x, weight, *maybe_bias, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None, cudnn_tune=None, cudnn_off=None, workspace=None):
    lax = _lax()
    nd = x.ndim - 2
    strides = _tup(stride, nd)
    dil = _tup(dilate, nd)
    pads = _tup(pad, nd) if pad is not None else (0,) * nd
    padding = [(p, p) for p in pads]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    _conv_dimnums(x.ndim, layout))
    y = lax.conv_general_dilated(
        x, weight, window_strides=strides, padding=padding,
        rhs_dilation=dil, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=None)
    if not no_bias and maybe_bias:
        b = maybe_bias[0]
        if layout is not None and layout.endswith("C"):
            y = y + b  # channel-last: broadcasts over the trailing dim
        else:
            y = y + b.reshape((1, -1) + (1,) * nd)
    return y


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(x, weight, *maybe_bias, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1,
                  no_bias=True, layout=None, target_shape=None, workspace=None,
                  cudnn_tune=None, cudnn_off=None):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc —
    the gradient of Convolution wrt its input; weight layout
    (in, out/g, *k) channel-first, (in, *k, out/g) for NWC/NHWC/NDHWC).

    Lowered directly as a conv_general_dilated with lhs_dilation=strides on
    the spatially-flipped kernel — the exact gradient program, so XLA:TPU
    schedules it like any other conv (one MXU contraction, no scatter)."""
    lax = _lax()
    jnp = _jnp()
    nd = x.ndim - 2
    cl = layout is not None and layout.endswith("C")  # channel-last
    strides = _tup(stride, nd)
    pads = _tup(pad, nd) if pad is not None else (0,) * nd
    dil = _tup(dilate, nd)
    adjs = _tup(adj, nd) if adj is not None else (0,) * nd
    if cl:
        dn_str = {3: ("NHC", "IHO", "NHC"), 4: ("NHWC", "IHWO", "NHWC"),
                  5: ("NDHWC", "IDHWO", "NDHWC")}[x.ndim]
        sp_axes = tuple(range(1, weight.ndim - 1))
        k_shape = weight.shape[1:-1]
    else:
        dn_str = {3: ("NCH", "IOH", "NCH"), 4: ("NCHW", "IOHW", "NCHW"),
                  5: ("NCDHW", "IODHW", "NCDHW")}[x.ndim]
        sp_axes = tuple(range(2, weight.ndim))
        k_shape = weight.shape[2:]
    w_flip = jnp.flip(weight, axis=sp_axes)
    padding = [(d * (k - 1) - p, d * (k - 1) - p + a)
               for k, p, d, a in zip(k_shape, pads, dil, adjs)]

    if num_group > 1:
        # one grouped conv instead of a per-group python loop: reorder so
        # XLA's native grouped-conv kernel handles the partitioning (group
        # gi of the lhs channels maps to output block gi, matching the
        # reference's layout)
        g = num_group
        cin_g = w_flip.shape[0] // g
        if cl:
            # (in, *k, out/g) -> (in/g, *k, g*out/g)
            og = w_flip.shape[-1]
            sp = w_flip.shape[1:-1]
            w_flip = w_flip.reshape((g, cin_g) + sp + (og,))
            w_flip = jnp.moveaxis(w_flip, 0, -2)
            w_flip = w_flip.reshape((cin_g,) + sp + (g * og,))
        else:
            # (in, out/g, *k) -> (in/g, g*out/g, *k)
            og = w_flip.shape[1]
            w_flip = w_flip.reshape((g, cin_g, og) + w_flip.shape[2:])
            w_flip = jnp.swapaxes(w_flip, 0, 1)
            w_flip = w_flip.reshape((cin_g, g * og) + w_flip.shape[3:])
    dn = lax.conv_dimension_numbers(x.shape, w_flip.shape, dn_str)
    y = lax.conv_general_dilated(
        x, w_flip, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and maybe_bias:
        b = maybe_bias[0]
        y = y + b if cl else y + b.reshape((1, -1) + (1,) * nd)
    return y


# ==========================================================================
# Pooling (reference: src/operator/nn/pooling.cc)
# ==========================================================================
@register("Pooling", aliases=("pooling",))
def pooling(x, kernel=None, pool_type="max", stride=None, pad=None,
            global_pool=False, pooling_convention="valid", count_include_pad=True,
            cudnn_off=None, layout=None):
    lax = _lax()
    jnp = _jnp()
    nd = x.ndim - 2
    # channel-last layouts (NWC/NHWC/NDHWC): spatial dims are 1..nd
    cl = layout is not None and layout.endswith("C")
    if global_pool:
        axes = tuple(range(1, x.ndim - 1)) if cl else tuple(range(2, x.ndim))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    k = _tup(kernel, nd)
    s = _tup(stride if stride is not None else 1, nd)
    p = _tup(pad or 0, nd)
    sp0 = 1 if cl else 2  # first spatial dim
    pads = [(pp, pp) for pp in p]
    if pooling_convention == "full":
        # ceil-mode: extend padding on the high side so ceil division is covered
        for i in range(nd):
            in_sz = x.shape[sp0 + i] + 2 * p[i]
            rem = (in_sz - k[i]) % s[i]
            if rem:
                pads[i] = (p[i], p[i] + s[i] - rem)
    if cl:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        padding = ((0, 0),) + tuple(pads) + ((0, 0),)
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        padding = ((0, 0), (0, 0)) + tuple(pads)
    if pool_type == "max":
        init = -_np.inf
        y = lax.reduce_window(x, init, lax.max, window, strides, padding)
        return y
    if pool_type in ("avg", "sum"):
        y = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return y
        if count_include_pad:
            denom = 1.0
            for kk in k:
                denom *= kk
            return y / denom
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return y / cnt
    if pool_type == "lp":
        y = lax.reduce_window(jnp.abs(x) ** 2, 0.0, lax.add, window, strides, padding)
        return jnp.sqrt(y)
    raise ValueError(f"unknown pool_type {pool_type}")


@register("UpSampling", aliases=("upsampling",))
def upsampling(x, *weights, scale=2, sample_type="nearest", num_filter=0,
               multi_input_mode=None, num_args=1, workspace=None):
    jnp = _jnp()
    if sample_type == "nearest":
        y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        return y
    # bilinear
    import jax

    n, c, h, w = x.shape
    return jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")


@register("BilinearResize2D", aliases=("bilinear_resize2d",))
def bilinear_resize2d(x, height=None, width=None, scale_height=None,
                      scale_width=None, mode="size"):
    import jax

    n, c, h, w = x.shape
    th = height if height else int(h * scale_height)
    tw = width if width else int(w * scale_width)
    return jax.image.resize(x, (n, c, th, tw), method="bilinear")


# ==========================================================================
# Activations (reference: src/operator/nn/activation.cc, leaky_relu.cc)
# ==========================================================================
@register("Activation", aliases=("activation",))
def activation(x, act_type="relu"):
    jnp = _jnp()
    nn = _nn()
    if act_type == "relu":
        return jnp.maximum(x, 0)
    if act_type == "sigmoid":
        return nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return nn.softplus(x)
    if act_type == "softsign":
        return x / (1 + jnp.abs(x))
    raise ValueError(f"unknown act_type {act_type}")


@register("LeakyReLU", aliases=("leaky_relu",))
def leaky_relu(x, *maybe_gamma, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    jnp = _jnp()
    nn = _nn()
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        gamma = maybe_gamma[0]
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 and gamma.ndim == 1 else gamma
        return jnp.where(x > 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1))
    if act_type == "selu":
        return nn.selu(x)
    if act_type == "gelu":
        return nn.gelu(x, approximate=False)
    if act_type == "rrelu":  # eval-mode deterministic: mean slope
        s = (lower_bound + upper_bound) / 2
        return jnp.where(x > 0, x, s * x)
    raise ValueError(f"unknown act_type {act_type}")


@register("softmax")
def softmax(x, axis=-1, temperature=None, length=None):
    nn = _nn()
    jnp = _jnp()
    if temperature:
        x = x / temperature
    if length is not None:
        steps = jnp.arange(x.shape[axis])
        shp = [1] * x.ndim
        shp[axis] = x.shape[axis]
        mask = steps.reshape(shp) < length.reshape((-1,) + (1,) * (x.ndim - 1))
        x = jnp.where(mask, x, -1e30)
    return nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(x, axis=-1, temperature=None):
    if temperature:
        x = x / temperature
    return _nn().log_softmax(x, axis=axis)


@register("softmin")
def softmin(x, axis=-1):
    return _nn().softmax(-x, axis=axis)


@register("smooth_l1")
def smooth_l1(x, scalar=1.0):
    jnp = _jnp()
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


# ==========================================================================
# Normalization (reference: src/operator/nn/{batch_norm,layer_norm,...}.cc)
# ==========================================================================
@register("BatchNorm", aliases=("batch_norm",), nout=3)
def batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5, momentum=0.9,
               fix_gamma=True, use_global_stats=False, axis=1,
               cudnn_off=None, output_mean_var=False, training=False):
    """Returns (out, new_moving_mean, new_moving_var).

    The reference mutates the moving stats inside the op (stateful FCompute);
    here the layer writes outputs 1-2 back into the running-stat parameters
    (functional-state threading; under ``hybridize`` these ride as extra jit
    outputs).
    """
    jnp = _jnp()
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    axis = axis % x.ndim  # accept axis=-1 (channel-last layouts)
    axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    # mixed-precision contract (reference: BN runs multi-precision under AMP):
    # statistics accumulate in fp32 even for bf16/fp16 activations (XLA's
    # reduction accumulator is fp32 once the operand is upcast per-element
    # inside the fused reduce); the normalize itself stays in the activation
    # dtype so the residuals saved for backward don't double HBM traffic.
    in_dtype = x.dtype
    f32 = jnp.float32
    if training and not use_global_stats:
        mean = jnp.mean(x.astype(f32), axis=axes)
        var = jnp.var(x.astype(f32), axis=axes)
        new_mean = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_var = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    scale = (g.astype(f32) * _lax().rsqrt(var.astype(f32) + eps))
    bias = beta.astype(f32) - mean.astype(f32) * scale
    out = (x * scale.reshape(shape).astype(in_dtype)
           + bias.reshape(shape).astype(in_dtype))
    from jax import lax as _l

    return out, _l.stop_gradient(new_mean), _l.stop_gradient(new_var)


@register("LayerNorm", aliases=("layer_norm",))
def layer_norm(x, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    jnp = _jnp()
    in_dtype = x.dtype
    xf = x.astype(jnp.float32) if in_dtype != jnp.float32 else x
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    xh = (xf - mean) * _lax().rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return (xh * gamma.reshape(shape) + beta.reshape(shape)).astype(in_dtype)


@register("GroupNorm", aliases=("group_norm",))
def group_norm(x, gamma, beta, num_groups=1, eps=1e-5, output_mean_var=False):
    jnp = _jnp()
    n, c = x.shape[:2]
    rest = x.shape[2:]
    xg = x.reshape((n, num_groups, c // num_groups) + rest)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xh = ((xg - mean) * _lax().rsqrt(var + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * len(rest)
    return xh * gamma.reshape(shape) + beta.reshape(shape)


@register("InstanceNorm", aliases=("instance_norm",))
def instance_norm(x, gamma, beta, eps=1e-3):
    jnp = _jnp()
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    xh = (x - mean) * _lax().rsqrt(var + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return xh * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization", aliases=("l2_normalization",))
def l2_normalization(x, eps=1e-10, mode="instance"):
    jnp = _jnp()
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / nrm


@register("LRN", aliases=("lrn",))
def lrn(x, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    jnp = _jnp()
    sq = jnp.square(x)
    half = nsize // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(x)
    for i in range(nsize):
        acc = acc + pad[:, i:i + x.shape[1]]
    return x / jnp.power(knorm + alpha * acc / nsize, beta)


# ==========================================================================
# Dropout (reference: src/operator/nn/dropout.cc) — needs RNG key
# ==========================================================================
@register("Dropout", aliases=("dropout",), needs_rng=True)
def dropout_op(key, x, p=0.5, mode="training", axes=None, training=False,
               cudnn_off=None):
    from jax import random as jr

    jnp = _jnp()
    if not training and mode != "always":
        return x
    if p <= 0.0:
        return x
    shape = x.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
    keep = 1.0 - p
    mask = jr.bernoulli(key, keep, shape).astype(x.dtype) / keep
    return x * mask


# ==========================================================================
# Loss-layer ops (reference: src/operator/softmax_output.cc etc.)
# ==========================================================================
@register("SoftmaxOutput", aliases=("softmax_output", "SoftmaxActivation"))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Forward = softmax; backward = (p - onehot(label)) * grad_scale,
    *ignoring* the incoming out_grad — a loss layer, exactly like the
    reference (src/operator/softmax_output.cc).  Implemented with
    jax.custom_vjp to pin that gradient."""
    import jax

    nn = _nn()
    jnp = _jnp()

    @jax.custom_vjp
    def _so(d, l):
        return nn.softmax(d, axis=-1)

    def _fwd(d, l):
        p = nn.softmax(d, axis=-1)
        return p, (p, l)

    def _bwd(res, g):
        p, l = res
        oh = nn.one_hot(l.astype(_np.int32), p.shape[-1], dtype=p.dtype)
        grad = (p - oh)
        if use_ignore:
            mask = (l != ignore_label).astype(p.dtype)
            grad = grad * mask[..., None]
        if normalization == "batch":
            grad = grad / p.shape[0]
        elif normalization == "valid" and use_ignore:
            cnt = jnp.maximum(jnp.sum(l != ignore_label), 1)
            grad = grad / cnt
        return grad * grad_scale, jnp.zeros_like(l)

    _so.defvjp(_fwd, _bwd)
    return _so(data, label)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(x, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    import jax

    @jax.custom_vjp
    def _ml(v):
        return v

    def _fwd(v):
        return v, v

    def _bwd(res, g):
        jnp = _jnp()
        grad = jnp.ones_like(res) * grad_scale
        if normalization == "batch":
            grad = grad / res.shape[0]
        return (grad,)

    _ml.defvjp(_fwd, _bwd)
    return _ml(x)


@register("LinearRegressionOutput", aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0):
    import jax

    @jax.custom_vjp
    def _lr(d, l):
        return d

    def _fwd(d, l):
        return d, (d, l)

    def _bwd(res, g):
        d, l = res
        jnp = _jnp()
        return ((d - l.reshape(d.shape)) * grad_scale / d.shape[0] * 1.0,
                jnp.zeros_like(l))

    _lr.defvjp(_fwd, _bwd)
    return _lr(data, label)


@register("LogisticRegressionOutput", aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0):
    import jax

    nn = _nn()

    @jax.custom_vjp
    def _lr(d, l):
        return nn.sigmoid(d)

    def _fwd(d, l):
        return nn.sigmoid(d), (nn.sigmoid(d), l)

    def _bwd(res, g):
        p, l = res
        jnp = _jnp()
        return ((p - l.reshape(p.shape)) * grad_scale, jnp.zeros_like(l))

    _lr.defvjp(_fwd, _bwd)
    return _lr(data, label)


@register("MAERegressionOutput", aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0):
    import jax

    @jax.custom_vjp
    def _lr(d, l):
        return d

    def _fwd(d, l):
        return d, (d, l)

    def _bwd(res, g):
        d, l = res
        jnp = _jnp()
        return (jnp.sign(d - l.reshape(d.shape)) * grad_scale, jnp.zeros_like(l))

    _lr.defvjp(_fwd, _bwd)
    return _lr(data, label)


@register("CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """data: (seq, batch, alphabet). Reference: src/operator/nn/ctc_loss.cc.
    TPU impl: optax.ctc_loss (blank must be 0 — 'first')."""
    import optax

    jnp = _jnp()
    seq, b, a = data.shape
    logits = jnp.transpose(data, (1, 0, 2))  # (B, T, A)
    if use_data_lengths and data_lengths is not None:
        t_steps = jnp.arange(seq)[None, :]
        logitpad = (t_steps >= data_lengths[:, None]).astype(jnp.float32)
    else:
        logitpad = jnp.zeros((b, seq), jnp.float32)
    labels = label.astype(_np.int32)
    if use_label_lengths and label_lengths is not None:
        l_steps = jnp.arange(labels.shape[1])[None, :]
        labelpad = (l_steps >= label_lengths[:, None]).astype(jnp.float32)
    else:
        labelpad = (labels <= 0).astype(jnp.float32)  # 0 used as padding token
    return optax.ctc_loss(logits, logitpad, labels, labelpad)


# ==========================================================================
# Fused RNN op (reference: src/operator/rnn.cc "RNN" — the cuDNN-style
# fused multi-layer recurrence with the FLAT parameter vector; the symbol
# scripts' sym.RNN and mx.rnn.FusedRNNCell surface).
# TPU-native: each layer/direction is a lax.scan whose i2h projection is
# hoisted out of the loop as one big (T*N, ni)x(ni, G*nh) GEMM on the MXU;
# only the h2h recurrence stays sequential.
# ==========================================================================
_RNN_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


def _rnn_param_layout(mode, input_size, state_size, num_layers, ndir):
    """Yield (kind, shape) in the reference's flat layout: all weights
    layer-major (i2h then h2h per direction), then all biases."""
    g = _RNN_GATES[mode]
    nh = state_size
    shapes = []
    for layer in range(num_layers):
        ni = input_size if layer == 0 else nh * ndir
        for _ in range(ndir):
            shapes.append(("i2h_weight", (g * nh, ni)))
            shapes.append(("h2h_weight", (g * nh, nh)))
    for layer in range(num_layers):
        for _ in range(ndir):
            shapes.append(("i2h_bias", (g * nh,)))
            shapes.append(("h2h_bias", (g * nh,)))
    return shapes


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False):
    """Total flat parameter count (reference: rnn-inl.h GetRnnParamSize)."""
    ndir = 2 if bidirectional else 1
    return sum(int(_np.prod(s)) for _, s in _rnn_param_layout(
        mode, input_size, state_size, num_layers, ndir))


def _rnn_scan_dir(jnp, mode, xs, h0, c0, wi, wh, bi, bh,
                  clip_min=None, clip_max=None, clip_nan=False,
                  seq_len=None):
    """xs (T, N, ni) -> (hs (T, N, nh), h_final, c_final|None).

    With ``seq_len`` (N,) int32, steps at t >= seq_len[n] neither advance
    the carry nor emit output for sample n (reference: rnn.cc
    use_sequence_length masking): the final state is the state at each
    sample's last valid step and padded outputs are zero."""
    import jax
    from jax import nn as jnn

    i2h_all = jnp.einsum("tni,gi->tng", xs, wi) + bi
    lstm = mode == "lstm"
    if lstm:
        def core(carry, i2h_t):
            h_prev, c_prev = carry
            gates = i2h_t + h_prev @ wh.T + bh
            i, f, g_, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jnn.sigmoid(i), jnn.sigmoid(f), jnn.sigmoid(o)
            c = f * c_prev + i * jnp.tanh(g_)
            # reference rnn.cc clips the cell state EVERY step so the
            # recurrence stays bounded, not just the returned final state
            if clip_nan:
                c = jnp.nan_to_num(c, nan=0.0)
            if clip_min is not None or clip_max is not None:
                c = jnp.clip(c, clip_min, clip_max)
            h = o * jnp.tanh(c)
            return (h, c), h

        carry0 = (h0, c0)
    elif mode == "gru":
        def core(h_prev, i2h_t):
            h2h = h_prev @ wh.T + bh
            ir, iz, in_ = jnp.split(i2h_t, 3, axis=-1)
            hr, hz, hn = jnp.split(h2h, 3, axis=-1)
            r = jnn.sigmoid(ir + hr)
            z = jnn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            h = (1 - z) * n + z * h_prev
            return h, h

        carry0 = h0
    else:
        act = (lambda v: jnp.maximum(v, 0)) if mode == "rnn_relu" \
            else jnp.tanh

        def core(h_prev, i2h_t):
            h = act(i2h_t + h_prev @ wh.T + bh)
            return h, h

        carry0 = h0

    if seq_len is None:
        cf_, hs = jax.lax.scan(core, carry0, i2h_all)
    else:
        def step(carry, inp):
            i2h_t, t = inp
            new_carry, h = core(carry, i2h_t)
            m = (t < seq_len).astype(xs.dtype)[:, None]
            if lstm:
                new_carry = (m * new_carry[0] + (1 - m) * carry[0],
                             m * new_carry[1] + (1 - m) * carry[1])
            else:
                new_carry = m * new_carry + (1 - m) * carry
            return new_carry, h * m

        cf_, hs = jax.lax.scan(step, carry0,
                               (i2h_all, jnp.arange(xs.shape[0])))
    if lstm:
        return hs, cf_[0], cf_[1]
    return hs, cf_, None


def _reverse_sequence(jnp, x, seq_len):
    """Reverse each sample's valid prefix along axis 0, leaving padding in
    place — delegates to the registered sequence_reverse kernel so the RNN
    path and the SequenceReverse op cannot diverge."""
    from .tensor import sequence_reverse

    return sequence_reverse(x, seq_len, use_sequence_length=True, axis=0)


@register("RNN", aliases=("rnn",), nout="dynamic", needs_rng=True)
def fused_rnn(rng_key, data, parameters, *maybe_states, state_size=None,
              num_layers=1, mode="lstm", bidirectional=False, p=0.0,
              state_outputs=False, training=False, projection_size=None,
              lstm_state_clip_min=None, lstm_state_clip_max=None,
              lstm_state_clip_nan=False, use_sequence_length=False):
    """data (T, N, C) [the reference's TNC layout], parameters: the flat
    vector (see rnn_param_size), optional state (nl*nd, N, nh) and, for
    lstm, state_cell.  With ``use_sequence_length`` the LAST input is
    sequence_length (N,): steps past each sample's length neither advance
    states nor emit output, and the reverse direction runs over each
    sample's valid prefix (reference: rnn.cc use_sequence_length).
    Returns out, or (out, state_h[, state_cell]) when state_outputs.
    Dropout p applies between layers when training."""
    jnp = _jnp()
    if projection_size:
        raise ValueError("RNN projection_size is not supported")
    T, N, C = data.shape
    nh, nl = int(state_size), int(num_layers)
    ndir = 2 if bidirectional else 1
    layout = _rnn_param_layout(mode, C, nh, nl, ndir)
    flat = parameters
    pieces = []
    off = 0
    for _, shp in layout:
        n = int(_np.prod(shp))
        pieces.append(flat[off:off + n].reshape(shp))
        off += n
    if off != flat.shape[0]:
        raise ValueError(
            f"RNN: parameter vector has {flat.shape[0]} elements, layout "
            f"needs {off} (mode={mode}, input={C}, hidden={nh}, "
            f"layers={nl}, dirs={ndir})")
    n_w = 2 * nl * ndir
    weights = pieces[:n_w]
    biases = pieces[n_w:]
    states = list(maybe_states)
    seq_len = None
    if use_sequence_length:
        if not states:
            raise ValueError("RNN use_sequence_length=True requires a "
                             "sequence_length input")
        seq_len = jnp.asarray(states.pop()).astype(jnp.int32)
    h_all = states[0] if states else jnp.zeros((nl * ndir, N, nh), data.dtype)
    c_all = states[1] if mode == "lstm" and len(states) > 1 else \
        jnp.zeros((nl * ndir, N, nh), data.dtype)
    out = data
    out_h, out_c = [], []
    for layer in range(nl):
        layer_outs = []
        for d in range(ndir):
            idx = layer * ndir + d
            wi, wh = weights[2 * idx], weights[2 * idx + 1]
            bi, bh = biases[2 * idx], biases[2 * idx + 1]
            if d == 0:
                seq = out
            elif seq_len is None:
                seq = jnp.flip(out, axis=0)
            else:
                seq = _reverse_sequence(jnp, out, seq_len)
            hs, hf, cf = _rnn_scan_dir(jnp, mode, seq, h_all[idx],
                                       c_all[idx], wi, wh, bi, bh,
                                       clip_min=lstm_state_clip_min,
                                       clip_max=lstm_state_clip_max,
                                       clip_nan=lstm_state_clip_nan,
                                       seq_len=seq_len)
            if d == 1:
                hs = jnp.flip(hs, axis=0) if seq_len is None else \
                    _reverse_sequence(jnp, hs, seq_len)
            layer_outs.append(hs)
            out_h.append(hf)
            if cf is not None:
                out_c.append(cf)
        out = layer_outs[0] if ndir == 1 else \
            jnp.concatenate(layer_outs, axis=-1)
        if p > 0 and training and layer < nl - 1:
            from jax import random as jr

            keep = 1.0 - p
            key = jr.fold_in(rng_key, layer)
            out = out * jr.bernoulli(key, keep, out.shape).astype(
                out.dtype) / keep
    if not state_outputs:
        return out
    outs = (out, jnp.stack(out_h, axis=0))
    if mode == "lstm":
        outs = outs + (jnp.stack(out_c, axis=0),)
    return outs


# ==========================================================================
# Spatial transformer family (reference: src/operator/
# {grid_generator,bilinear_sampler,spatial_transformer}.cc — STN ops).
# TPU-first: the sampling is a dense gather+lerp (fuses in XLA), the grid
# math is elementwise; no atomics like the CUDA backward needed — jax
# derives the scatter transpose.
# ==========================================================================
@register("GridGenerator", aliases=("grid_generator",))
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """affine: data (N, 6) -> sampling grid (N, 2, H, W) in [-1, 1]
    (x then y rows, the reference's layout); warp: data (N, 2, H, W)
    flow field -> normalized grid."""
    jnp = _jnp()
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape((-1, 2, 3))
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gx, gy = jnp.meshgrid(xs, ys)          # (h, w)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones]).reshape(3, -1)   # (3, h*w)
        out = jnp.einsum("nij,jk->nik", theta, base)      # (n, 2, h*w)
        return out.reshape((-1, 2, h, w))
    if transform_type == "warp":
        n, _, h, w = data.shape
        ys = jnp.arange(h, dtype=data.dtype)
        xs = jnp.arange(w, dtype=data.dtype)
        gx, gy = jnp.meshgrid(xs, ys)
        x = (data[:, 0] + gx) * (2.0 / max(w - 1, 1)) - 1.0
        y = (data[:, 1] + gy) * (2.0 / max(h - 1, 1)) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError(f"unknown transform_type {transform_type}")


def _bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,h,w) normalized [-1,1] -> (N,C,h,w);
    zero padding outside (reference BilinearSampler border semantics)."""
    jnp = _jnp()
    n, c, H, W = data.shape
    x = (grid[:, 0] + 1.0) * (W - 1) / 2.0     # (n, h, w)
    y = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def gather(yi, xi):
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype("int32")
        yc = jnp.clip(yi, 0, H - 1).astype("int32")
        # (n, c, h, w) gather per batch
        v = data[jnp.arange(n)[:, None, None], :, yc, xc]   # (n,h,w,c)
        v = jnp.moveaxis(v, -1, 1)
        return v * inb[:, None, :, :]

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    return (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
            + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)


@register("BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, cudnn_off=None):
    return _bilinear_sample(data, grid)


@register("SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """Affine STN: loc (N, 6) localization -> grid -> bilinear sample
    (reference: spatial_transformer.cc — affine is the only transform the
    reference op supports either)."""
    if transform_type != "affine":
        raise ValueError("SpatialTransformer supports transform_type="
                         "'affine' only (reference parity); build warp "
                         "grids with GridGenerator + BilinearSampler")
    if sampler_type != "bilinear":
        raise ValueError("SpatialTransformer supports sampler_type="
                         "'bilinear' only")
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return _bilinear_sample(data, grid)
