"""Flash attention: Pallas TPU kernel + jax fallback.

Reference scope: MXNet 1.x has NO fused attention — GluonNLP ran full O(L²)
softmax(QKᵀ)V through `src/operator/contrib/transformer.cc`'s interleaved
matmuls (SURVEY.md §6.7).  This module is the net-new TPU capability the
BASELINE Llama config requires: an online-softmax blocked kernel that keeps
the L×L score matrix out of HBM, tiled to the MXU (128-lane blocks), with a
memory-efficient blockwise backward (lax.scan recompute — O(L) memory).

Layout: (batch, heads, seq, head_dim) — q_heads may be a multiple of
kv_heads (GQA).
"""
from __future__ import annotations

import functools

import numpy as _np

NEG_INF = -1e30


def _use_pallas(q):
    import jax

    if q.shape[-1] % 128 != 0 and q.shape[-1] not in (64, 128, 256):
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return platform == "tpu" and q.shape[-2] >= 256


# --------------------------------------------------------------------------
# jax reference path (CPU tests, short sequences, fallback)
# --------------------------------------------------------------------------
def _mha_with_lse(q, k, v, causal, sm_scale):
    import jax.numpy as jnp

    b, hq, lq, d = q.shape
    hkv = k.shape[1]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        lk = k.shape[2]
        mask = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        scores = jnp.where(mask, scores, NEG_INF)
    m = scores.max(axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    denom = e.sum(axis=-1, keepdims=True)
    p = e / denom
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    lse = (m + jnp.log(denom))[..., 0]
    return o, lse


def _mha_reference(q, k, v, causal, sm_scale):
    return _mha_with_lse(q, k, v, causal, sm_scale)[0]


# --------------------------------------------------------------------------
# Pallas forward kernel
# --------------------------------------------------------------------------
def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal,
                   sm_scale, seq_k, diag_offset=0):
    """One (q-block × full-K sweep): online softmax accumulation.

    Grid: (batch*heads, num_q_blocks).  Block shapes:
      q_ref (block_q, d) VMEM; k_ref/v_ref (seq_k, d) VMEM (whole K/V row
      for this head — fine at the seq lengths VMEM allows; longer sequences
      ring through context parallelism instead).
    """
    import jax
    import jax.numpy as jnp

    block_q, d = q_ref.shape
    qi = pl_program_id(1)

    q = q_ref[:].astype(jnp.float32) * sm_scale

    m = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = pl_load(k_ref, kb, block_k).astype(jnp.float32)
        v_blk = pl_load(v_ref, kb, block_k).astype(jnp.float32)
        s = q @ k_blk.T                                     # (bq, bk)
        if causal:
            q_pos = diag_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    if causal:
        # skip fully-masked K blocks beyond this q block (offset-aware)
        max_kb = jnp.minimum(
            ((qi + 1) * block_q + diag_offset + block_k - 1) // block_k,
            num_kb)
    else:
        max_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, max_kb, body, (m, l, acc))

    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse tile is (8, block_q) to satisfy TPU (sublane, lane) tiling; the
    # vector is broadcast across the 8 sublanes and row 0 is read back
    lse = (m + jnp.log(l)).astype(lse_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(lse[None, :], lse_ref.shape)


def pl_program_id(axis):
    from jax.experimental import pallas as pl

    return pl.program_id(axis)


def pl_load(ref, block_idx, block_size):
    from jax.experimental import pallas as pl

    return ref[pl.ds(block_idx * block_size, block_size), :]


def _fa_block_sizes():
    """Forward kernel tile sizes, resolved through the tuning funnel
    (MXNET_FLASH_BLOCK_Q / MXNET_FLASH_BLOCK_KV pins > MXNET_TUNE=1
    stored winners > 128 = one MXU lane tile).  Re-read per call on
    purpose — the op is jit_safe=False exactly so sweeps/trials can
    vary the tile between calls.  Values must divide the padded
    sequence length."""
    try:
        from .. import tuning as _tuning

        return (int(_tuning.resolve("flash_block_q")),
                int(_tuning.resolve("flash_block_kv")))
    except Exception:
        import os

        return (int(os.environ.get("MXNET_FLASH_BLOCK_Q", 128)),
                int(os.environ.get("MXNET_FLASH_BLOCK_KV", 128)))


def _fa_forward_pallas(q, k, v, causal, sm_scale, block_q=None, block_k=None):
    if block_q is None or block_k is None:
        bq, bk = _fa_block_sizes()
        block_q = bq if block_q is None else block_q
        block_k = bk if block_k is None else block_k
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    assert lq % block_q == 0 and lk % block_k == 0, (
        "sequence must be padded to the attention block size")

    grid = (b * h, lq // block_q)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)

    kernel = functools.partial(_fa_fwd_kernel, block_k=block_k,
                               causal=causal, sm_scale=sm_scale, seq_k=lk,
                               diag_offset=lk - lq)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, lk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, lk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, 8, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, lq), jnp.float32),
        ],
    )(qf, kf, vf)
    return o.reshape(b, h, lq, d), lse[:, 0, :].reshape(b, h, lq)


# --------------------------------------------------------------------------
# blockwise backward (jax, O(L) memory via scan recompute)
# --------------------------------------------------------------------------
def _fa_backward_blockwise(q, k, v, o, lse, g, causal, sm_scale,
                           block_k=512):
    import jax
    import jax.numpy as jnp

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_k = min(block_k, lk)
    if lk % block_k != 0:
        block_k = lk
    nkb = lk // block_k

    acc_t = jnp.result_type(q.dtype, jnp.float32)
    qf = q.astype(acc_t)
    gf = g.astype(acc_t)
    of = o.astype(acc_t)
    delta = jnp.sum(of * gf, axis=-1)                      # (b,h,lq)

    kb = k.reshape(b, h, nkb, block_k, d).astype(acc_t)
    vb = v.reshape(b, h, nkb, block_k, d).astype(acc_t)

    q_pos = jnp.arange(lq)

    def step(dq, idx):
        kblk = kb[:, :, idx]                               # (b,h,bk,d)
        vblk = vb[:, :, idx]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk) * sm_scale
        if causal:
            # same diagonal offset as the forward (q_i attends keys up to
            # i + lk - lq when lengths differ, e.g. decode)
            k_pos = idx * block_k + jnp.arange(block_k)
            mask = (q_pos[:, None] + (lk - lq)) >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # (b,h,q,bk)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vblk)
        ds = p * (dp - delta[..., None]) * sm_scale
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, jnp.arange(nkb))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, lk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, lk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# public op with custom vjp
# --------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _make_flash(causal, sm_scale_key):
    import jax
    import jax.numpy as jnp

    sm_scale = float(sm_scale_key)

    @jax.custom_vjp
    def flash(q, k, v):
        return _dispatch_fwd(q, k, v)[0]

    def _dispatch_fwd(q, k, v):
        if _use_pallas(q):
            o, lse = _fa_forward_pallas(q, k, v, causal, sm_scale)
        else:
            o, lse = _mha_with_lse(q, k, v, causal, sm_scale)
        return o, (q, k, v, o, lse)

    def fwd(q, k, v):
        o, res = _dispatch_fwd(q, k, v)
        return o, res

    def bwd(res, g):
        q, k, v, o, lse = res
        return _fa_backward_blockwise(q, k, v, o, lse, g, causal, sm_scale)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """q (B,Hq,Lq,D); k,v (B,Hkv,Lk,D) with Hq % Hkv == 0 (GQA)."""
    import jax.numpy as jnp

    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / _np.sqrt(d)
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        # GQA expansion OUTSIDE the custom_vjp: jnp.repeat's own vjp folds
        # the expanded-head grads back onto the kv heads
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    fn = _make_flash(bool(causal), float(sm_scale))
    return fn(q, k, v)


# registry entry --------------------------------------------------------------
from .registry import register


# jit_safe=False: the op re-reads MXNET_FLASH_BLOCK_{Q,KV} per call (the
# bench block sweep depends on that), so it must not be frozen into a cached
# eager executable; per-call overhead is irrelevant at attention sizes
@register("_contrib_flash_attention", aliases=("flash_attention",),
          jit_safe=False)
def flash_attention_op(q, k, v, causal=False, sm_scale=None):
    """Fused scaled-dot-product attention (net-new vs reference; the TPU
    answer to contrib/transformer.cc's unfused attention path)."""
    return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
