"""Tensor operator family: elemwise, broadcast, reduce, matrix, indexing,
init, ordering, linalg.

Reference: ``src/operator/tensor/*.{cc,cu,h}`` (~90k LoC of C++/CUDA kernels,
SURVEY.md §3.2).  TPU-native: each op is one pure jax function — XLA fuses
elementwise chains into single kernels (replacing the reference's NVRTC
pointwise-fusion pass) and tiles matmuls onto the MXU, so there is nothing to
hand-schedule here.  Gradients come from ``jax.vjp`` (≙ FGradient attrs).
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    from jax import lax

    return lax


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


# ==========================================================================
# elementwise unary  (reference: src/operator/tensor/elemwise_unary_op*.cc)
# ==========================================================================
def _unary(name, f, differentiable=True, aliases=()):
    def fn(x):
        return f(_jnp(), x)

    fn.__name__ = name
    register(name, differentiable=differentiable, aliases=aliases)(fn)


_unary("abs", lambda jnp, x: jnp.abs(x))
_unary("sign", lambda jnp, x: jnp.sign(x))
_unary("negative", lambda jnp, x: -x)
_unary("reciprocal", lambda jnp, x: 1.0 / x)
_unary("square", lambda jnp, x: jnp.square(x))
_unary("sqrt", lambda jnp, x: jnp.sqrt(x))
_unary("rsqrt", lambda jnp, x: 1.0 / jnp.sqrt(x))
_unary("cbrt", lambda jnp, x: jnp.cbrt(x))
_unary("rcbrt", lambda jnp, x: 1.0 / jnp.cbrt(x))
_unary("exp", lambda jnp, x: jnp.exp(x))
_unary("expm1", lambda jnp, x: jnp.expm1(x))
_unary("log", lambda jnp, x: jnp.log(x))
_unary("log2", lambda jnp, x: jnp.log2(x))
_unary("log10", lambda jnp, x: jnp.log10(x))
_unary("log1p", lambda jnp, x: jnp.log1p(x))
_unary("sin", lambda jnp, x: jnp.sin(x))
_unary("cos", lambda jnp, x: jnp.cos(x))
_unary("tan", lambda jnp, x: jnp.tan(x))
_unary("arcsin", lambda jnp, x: jnp.arcsin(x))
_unary("arccos", lambda jnp, x: jnp.arccos(x))
_unary("arctan", lambda jnp, x: jnp.arctan(x))
_unary("sinh", lambda jnp, x: jnp.sinh(x))
_unary("cosh", lambda jnp, x: jnp.cosh(x))
_unary("tanh", lambda jnp, x: jnp.tanh(x))
_unary("arcsinh", lambda jnp, x: jnp.arcsinh(x))
_unary("arccosh", lambda jnp, x: jnp.arccosh(x))
_unary("arctanh", lambda jnp, x: jnp.arctanh(x))
_unary("degrees", lambda jnp, x: jnp.degrees(x))
_unary("radians", lambda jnp, x: jnp.radians(x))
_unary("floor", lambda jnp, x: jnp.floor(x), differentiable=False)
_unary("ceil", lambda jnp, x: jnp.ceil(x), differentiable=False)
_unary("round", lambda jnp, x: jnp.round(x), differentiable=False)
_unary("rint", lambda jnp, x: jnp.rint(x), differentiable=False)
_unary("trunc", lambda jnp, x: jnp.trunc(x), differentiable=False)
# fix == truncate toward zero; jnp.trunc is the stable spelling (jnp.fix
# rides numpy's deprecation track)
_unary("fix", lambda jnp, x: jnp.trunc(x), differentiable=False)
_unary("gamma", lambda jnp, x: _gamma_impl(jnp, x))
_unary("gammaln", lambda jnp, x: _gammaln_impl(jnp, x))
_unary("erf", lambda jnp, x: _erf_impl(jnp, x))
_unary("erfinv", lambda jnp, x: _erfinv_impl(jnp, x))
_unary("relu", lambda jnp, x: jnp.maximum(x, 0))
_unary("sigmoid", lambda jnp, x: _sigmoid_impl(jnp, x))
_unary("softsign", lambda jnp, x: x / (1 + jnp.abs(x)))
_unary("logical_not", lambda jnp, x: (~(x != 0)).astype(x.dtype), differentiable=False)
_unary("identity", lambda jnp, x: x, aliases=("_copy", "stop_gradient_off"))
_unary("zeros_like", lambda jnp, x: jnp.zeros_like(x), differentiable=False)
_unary("ones_like", lambda jnp, x: jnp.ones_like(x), differentiable=False)
_unary("isnan", lambda jnp, x: jnp.isnan(x), differentiable=False)
_unary("isinf", lambda jnp, x: jnp.isinf(x), differentiable=False)
_unary("isfinite", lambda jnp, x: jnp.isfinite(x), differentiable=False)


def _sigmoid_impl(jnp, x):
    from jax import nn

    return nn.sigmoid(x)


def _erf_impl(jnp, x):
    from jax.scipy.special import erf

    return erf(x)


def _erfinv_impl(jnp, x):
    from jax.scipy.special import erfinv

    return erfinv(x)


def _gamma_impl(jnp, x):
    from jax.scipy.special import gammaln

    return jnp.exp(gammaln(x)) * jnp.sign(_reflection_sign(jnp, x))


def _reflection_sign(jnp, x):
    # gamma(x) sign for x<0 alternates; for the common positive domain this is 1
    return jnp.where(x > 0, 1.0, jnp.cos(jnp.pi * jnp.floor(x)) * 0 + 1.0)


def _gammaln_impl(jnp, x):
    from jax.scipy.special import gammaln

    return gammaln(x)


@register("stop_gradient", aliases=("BlockGrad", "block_grad"), differentiable=False)
def stop_gradient(x):
    return _lax().stop_gradient(x)


@register("clip")
def clip(x, a_min=None, a_max=None):
    return _jnp().clip(x, a_min, a_max)


@register("cast", aliases=("Cast", "amp_cast"))
def cast(x, dtype="float32"):
    jnp = _jnp()
    dt = jnp.bfloat16 if dtype == "bfloat16" else _np.dtype(dtype)
    return x.astype(dt)


# ==========================================================================
# elementwise binary (+broadcast, +scalar)
# (reference: src/operator/tensor/elemwise_binary*_op*.cc)
# jnp broadcasts natively, so elemwise_* and broadcast_* share impls.
# ==========================================================================
def _binary(name, f, differentiable=True, aliases=()):
    def fn(a, b):
        return f(_jnp(), a, b)

    fn.__name__ = name
    register(name, differentiable=differentiable, aliases=aliases)(fn)


_binary("broadcast_add", lambda jnp, a, b: a + b, aliases=("elemwise_add", "add"))
_binary("broadcast_sub", lambda jnp, a, b: a - b, aliases=("elemwise_sub", "subtract"))
_binary("broadcast_mul", lambda jnp, a, b: a * b, aliases=("elemwise_mul", "multiply"))
_binary("broadcast_div", lambda jnp, a, b: a / b, aliases=("elemwise_div", "divide"))
_binary("broadcast_mod", lambda jnp, a, b: jnp.mod(a, b), aliases=("mod",))
_binary("broadcast_power", lambda jnp, a, b: jnp.power(a, b), aliases=("power",))
_binary("broadcast_maximum", lambda jnp, a, b: jnp.maximum(a, b), aliases=("maximum",))
_binary("broadcast_minimum", lambda jnp, a, b: jnp.minimum(a, b), aliases=("minimum",))
_binary("broadcast_hypot", lambda jnp, a, b: jnp.hypot(a, b))
_binary("arctan2", lambda jnp, a, b: jnp.arctan2(a, b))
_binary("broadcast_equal", lambda jnp, a, b: (a == b).astype(_np.float32), differentiable=False, aliases=("equal",))
_binary("broadcast_not_equal", lambda jnp, a, b: (a != b).astype(_np.float32), differentiable=False, aliases=("not_equal",))
_binary("broadcast_greater", lambda jnp, a, b: (a > b).astype(_np.float32), differentiable=False, aliases=("greater",))
_binary("broadcast_greater_equal", lambda jnp, a, b: (a >= b).astype(_np.float32), differentiable=False, aliases=("greater_equal",))
_binary("broadcast_lesser", lambda jnp, a, b: (a < b).astype(_np.float32), differentiable=False, aliases=("lesser",))
_binary("broadcast_lesser_equal", lambda jnp, a, b: (a <= b).astype(_np.float32), differentiable=False, aliases=("lesser_equal",))
_binary("broadcast_logical_and", lambda jnp, a, b: ((a != 0) & (b != 0)).astype(_np.float32), differentiable=False, aliases=("logical_and",))
_binary("broadcast_logical_or", lambda jnp, a, b: ((a != 0) | (b != 0)).astype(_np.float32), differentiable=False, aliases=("logical_or",))
_binary("broadcast_logical_xor", lambda jnp, a, b: ((a != 0) ^ (b != 0)).astype(_np.float32), differentiable=False, aliases=("logical_xor",))


def _binary_scalar(name, f, differentiable=True):
    def fn(a, scalar=0.0, reverse=False):
        jnp = _jnp()
        s = scalar
        return f(jnp, s, a) if reverse else f(jnp, a, s)

    fn.__name__ = name + "_scalar"
    register(name + "_scalar", differentiable=differentiable)(fn)


_binary_scalar("broadcast_add", lambda jnp, a, b: a + b)
_binary_scalar("broadcast_sub", lambda jnp, a, b: a - b)
_binary_scalar("broadcast_mul", lambda jnp, a, b: a * b)
_binary_scalar("broadcast_div", lambda jnp, a, b: a / b)
_binary_scalar("broadcast_mod", lambda jnp, a, b: jnp.mod(a, b))
_binary_scalar("broadcast_power", lambda jnp, a, b: jnp.power(a, b))
_binary_scalar("broadcast_maximum", lambda jnp, a, b: jnp.maximum(a, b))
_binary_scalar("broadcast_minimum", lambda jnp, a, b: jnp.minimum(a, b))
_binary_scalar("broadcast_equal", lambda jnp, a, b: (a == b).astype(_np.float32), differentiable=False)
_binary_scalar("broadcast_not_equal", lambda jnp, a, b: (a != b).astype(_np.float32), differentiable=False)
_binary_scalar("broadcast_greater", lambda jnp, a, b: (a > b).astype(_np.float32), differentiable=False)
_binary_scalar("broadcast_greater_equal", lambda jnp, a, b: (a >= b).astype(_np.float32), differentiable=False)
_binary_scalar("broadcast_lesser", lambda jnp, a, b: (a < b).astype(_np.float32), differentiable=False)
_binary_scalar("broadcast_lesser_equal", lambda jnp, a, b: (a <= b).astype(_np.float32), differentiable=False)


@register("where")
def where(cond, x, y):
    return _jnp().where(cond != 0, x, y)


@register("maximum_n")
def maximum_n(*arrays):
    jnp = _jnp()
    out = arrays[0]
    for a in arrays[1:]:
        out = jnp.maximum(out, a)
    return out


@register("add_n", aliases=("ElementWiseSum", "sum_n"))
def add_n(*arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


# ==========================================================================
# reductions  (reference: src/operator/tensor/broadcast_reduce_op*.cc)
# ==========================================================================
def _reduce(name, f, differentiable=True, aliases=()):
    def fn(x, axis=None, keepdims=False, exclude=False):
        jnp = _jnp()
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            if isinstance(ax, int):
                ax = (ax,)
            ax = tuple(i for i in range(x.ndim) if i not in tuple(a % x.ndim for a in ax))
        return f(jnp, x, ax, keepdims)

    fn.__name__ = name
    register(name, differentiable=differentiable, aliases=aliases)(fn)


_reduce("sum", lambda jnp, x, ax, kd: jnp.sum(x, axis=ax, keepdims=kd), aliases=("sum_axis",))
_reduce("nansum", lambda jnp, x, ax, kd: jnp.nansum(x, axis=ax, keepdims=kd))
_reduce("mean", lambda jnp, x, ax, kd: jnp.mean(x, axis=ax, keepdims=kd))
_reduce("prod", lambda jnp, x, ax, kd: jnp.prod(x, axis=ax, keepdims=kd))
_reduce("nanprod", lambda jnp, x, ax, kd: jnp.nanprod(x, axis=ax, keepdims=kd))
_reduce("max", lambda jnp, x, ax, kd: jnp.max(x, axis=ax, keepdims=kd), aliases=("max_axis",))
_reduce("min", lambda jnp, x, ax, kd: jnp.min(x, axis=ax, keepdims=kd), aliases=("min_axis",))


@register("norm")
def norm(x, ord=2, axis=None, keepdims=False):
    jnp = _jnp()
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", differentiable=False)
def argmax(x, axis=None, keepdims=False):
    jnp = _jnp()
    r = jnp.argmax(x, axis=axis, keepdims=keepdims).astype(_np.float32)
    return r


@register("argmin", differentiable=False)
def argmin(x, axis=None, keepdims=False):
    return _jnp().argmin(x, axis=axis, keepdims=keepdims).astype(_np.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(x):
    return _jnp().argmax(x, axis=1).astype(_np.float32)


@register("moments", nout=2)
def moments(x, axes=None, keepdims=False):
    jnp = _jnp()
    ax = _norm_axis(axes)
    mean = jnp.mean(x, axis=ax, keepdims=keepdims)
    var = jnp.mean(jnp.square(x - jnp.mean(x, axis=ax, keepdims=True)), axis=ax,
                   keepdims=keepdims)
    return mean, var


# ==========================================================================
# matrix / shape manipulation (reference: src/operator/tensor/matrix_op.cc)
# ==========================================================================
@register("dot")
def dot(a, b, transpose_a=False, transpose_b=False):
    """MXNet dot: contracts last axis of a with first axis of b (after
    optional transposes).  Lowers straight to the MXU."""
    jnp = _jnp()
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    return jnp.tensordot(a, b, axes=1) if a.ndim > 1 or b.ndim > 1 else jnp.dot(a, b)


@register("batch_dot")
def batch_dot(a, b, transpose_a=False, transpose_b=False):
    jnp = _jnp()
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("matmul")
def matmul(a, b):
    return _jnp().matmul(a, b)


@register("reshape", aliases=("Reshape",))
def reshape(x, shape=None, reverse=False):
    return x.reshape(shape)


@register("transpose")
def transpose(x, axes=None):
    return _jnp().transpose(x, axes=axes)


@register("flatten", aliases=("Flatten",))
def flatten(x):
    return x.reshape((x.shape[0], -1))


@register("expand_dims")
def expand_dims(x, axis=0):
    return _jnp().expand_dims(x, axis)


@register("squeeze")
def squeeze(x, axis=None):
    return _jnp().squeeze(x, axis=_norm_axis(axis))


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(x, dim1=0, dim2=1):
    return _jnp().swapaxes(x, dim1, dim2)


@register("broadcast_to")
def broadcast_to(x, shape=None):
    jnp = _jnp()
    # MXNet allows 0 meaning "keep this dim"
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(x, axis=None, size=None):
    jnp = _jnp()
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    sizes = size if isinstance(size, (tuple, list)) else (size,)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("concat", aliases=("Concat",))
def concat(*arrays, dim=1):
    return _jnp().concatenate(arrays, axis=dim)


@register("stack")
def stack(*arrays, axis=0):
    return _jnp().stack(arrays, axis=axis)


@register("split", aliases=("SliceChannel", "slice_channel"), nout="dynamic")
def split(x, num_outputs=1, axis=1, squeeze_axis=False):
    jnp = _jnp()
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("slice", aliases=("crop",))
def slice_op(x, begin=None, end=None, step=None):
    idx = tuple(slice(b, e, s)
                for b, e, s in zip(begin, end, step or (None,) * len(begin)))
    return x[idx]


@register("_slice_key")
def _slice_key(x, key=None):
    """Internal: differentiable basic indexing (used by NDArray.__getitem__
    under autograd recording)."""
    return x[key]


@register("_scatter_set_key")
def _scatter_set_key(x, v, key=None):
    """Internal: differentiable sliced write (NDArray.__setitem__ under
    autograd recording — SURVEY.md hard-part 1: the reference records
    in-place writes as write-var engine ops; here the functional update's
    vjp routes cotangents to the untouched region of ``x`` and the written
    ``v``)."""
    return x.at[key].set(v.astype(x.dtype))


@register("slice_axis")
def slice_axis(x, axis=0, begin=0, end=None):
    jnp = _jnp()
    return _lax().slice_in_dim(x, begin, end if end is not None else x.shape[axis],
                               axis=axis)


@register("slice_like")
def slice_like(x, like, axes=None):
    tgt = list(x.shape)
    axes = axes or range(x.ndim)
    for a in axes:
        tgt[a] = like.shape[a]
    idx = tuple(slice(0, t) for t in tgt)
    return x[idx]


@register("tile")
def tile(x, reps=None):
    return _jnp().tile(x, reps)


@register("repeat")
def repeat(x, repeats=1, axis=None):
    return _jnp().repeat(x, repeats, axis=axis)


@register("reverse", aliases=("flip",))
def reverse(x, axis=0):
    return _jnp().flip(x, axis=_norm_axis(axis))


@register("pad", aliases=("Pad",))
def pad(x, mode="constant", pad_width=None, constant_value=0.0):
    jnp = _jnp()
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise ValueError(f"unknown pad mode {mode}")


@register("depth_to_space")
def depth_to_space(x, block_size=1):
    jnp = _jnp()
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, bs, bs, c // (bs * bs), h, w)
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return y.reshape(n, c // (bs * bs), h * bs, w * bs)


@register("space_to_depth")
def space_to_depth(x, block_size=1):
    jnp = _jnp()
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, c, h // bs, bs, w // bs, bs)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape(n, c * bs * bs, h // bs, w // bs)


@register("diag")
def diag(x, k=0):
    jnp = _jnp()
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


# ==========================================================================
# indexing ops (reference: src/operator/tensor/indexing_op.cc)
# ==========================================================================
@register("take")
def take(a, indices, axis=0, mode="clip"):
    jnp = _jnp()
    idx = indices.astype(_np.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("Embedding", aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    jnp = _jnp()
    idx = jnp.clip(data.astype(_np.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("one_hot", differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    from jax import nn

    jnp = _jnp()
    oh = nn.one_hot(indices.astype(_np.int32), depth, dtype=_np.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(_np.int32))
    return data[idx]


@register("scatter_nd", differentiable=False)
def scatter_nd(data, indices, shape=None):
    jnp = _jnp()
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices.astype(_np.int32))
    return out.at[idx].set(data)


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    jnp = _jnp()
    idx = jnp.clip(index.astype(_np.int32), 0, data.shape[axis] - 1)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=axis)
    return picked


@register("where_index", differentiable=False)
def where_index(x):
    # dynamic-size output: materialized on host; used only eagerly
    return _jnp().asarray(_np.argwhere(_np.asarray(x)))


@register("boolean_mask", differentiable=False)
def boolean_mask(data, index, axis=0):
    mask = _np.asarray(index) != 0
    return _jnp().asarray(_np.compress(mask, _np.asarray(data), axis=axis))


@register("index_array", differentiable=False, creation=False)
def index_array(data, axes=None):
    jnp = _jnp()
    idx = jnp.stack(jnp.meshgrid(*[jnp.arange(s) for s in data.shape],
                                 indexing="ij"), axis=-1)
    if axes is not None:
        idx = idx[..., list(axes)]
    return idx.astype(_np.int64)


@register("sequence_mask", aliases=("SequenceMask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    jnp = _jnp()
    if sequence_length is None or not use_sequence_length:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:  # axis == 1
        mask = steps[None, :] < sequence_length[:, None]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


@register("sequence_last", aliases=("SequenceLast",))
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, -1, axis=axis)
    idx = (sequence_length - 1).astype(_np.int32)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=axis
    ).squeeze(axis)


@register("sequence_reverse", aliases=("SequenceReverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    rev_idx = sequence_length[None, :] - 1 - steps[:, None]
    rev_idx = jnp.where(rev_idx >= 0, rev_idx, steps[:, None]).astype(_np.int32)
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)


# ==========================================================================
# init ops (reference: src/operator/tensor/init_op.cc)
# ==========================================================================
@register("zeros", creation=True, differentiable=False)
def zeros(shape=None, dtype="float32"):
    return _jnp().zeros(shape, dtype=_dt(dtype))


@register("ones", creation=True, differentiable=False)
def ones(shape=None, dtype="float32"):
    return _jnp().ones(shape, dtype=_dt(dtype))


@register("full", creation=True, differentiable=False)
def full(shape=None, val=0.0, dtype="float32"):
    return _jnp().full(shape, val, dtype=_dt(dtype))


@register("arange", creation=True, differentiable=False)
def arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32"):
    jnp = _jnp()
    r = jnp.arange(start, stop, step, dtype=_dt(dtype))
    if repeat != 1:
        r = jnp.repeat(r, repeat)
    return r


@register("linspace", creation=True, differentiable=False)
def linspace(start=0, stop=1, num=50, endpoint=True, dtype="float32"):
    return _jnp().linspace(start, stop, num, endpoint=endpoint, dtype=_dt(dtype))


@register("eye", creation=True, differentiable=False)
def eye(N=1, M=0, k=0, dtype="float32"):
    return _jnp().eye(int(N), int(M) if M else None, k=int(k), dtype=_dt(dtype))


def _dt(dtype):
    if dtype == "bfloat16" or dtype is None and False:
        return _jnp().bfloat16
    return _np.dtype(dtype)


# ==========================================================================
# ordering (reference: src/operator/tensor/ordering_op.cc)
# ==========================================================================
@register("sort")
def sort(x, axis=-1, is_ascend=True):
    jnp = _jnp()
    r = jnp.sort(x, axis=axis)
    return r if is_ascend else jnp.flip(r, axis=axis)


@register("argsort", differentiable=False)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    jnp = _jnp()
    r = jnp.argsort(x, axis=axis)
    if not is_ascend:
        r = jnp.flip(r, axis=axis)
    return r.astype(_np.dtype(dtype))


@register("topk", differentiable=False, nout="dynamic")
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    jnp = _jnp()
    vals = x if not is_ascend else -x
    if axis != -1 and axis != x.ndim - 1:
        vals_m = jnp.moveaxis(vals, axis, -1)
    else:
        vals_m = vals
    top_v, top_i = _lax().top_k(vals_m, k)
    if is_ascend:
        top_v = -top_v
    if axis != -1 and axis != x.ndim - 1:
        top_v = jnp.moveaxis(top_v, -1, axis)
        top_i = jnp.moveaxis(top_i, -1, axis)
    if ret_typ == "indices":
        return top_i.astype(_np.dtype(dtype))
    if ret_typ == "value":
        return top_v
    if ret_typ == "both":
        return top_v, top_i.astype(_np.dtype(dtype))
    if ret_typ == "mask":
        from jax import nn as _jnn

        # top_i: (..., k) indices into the (moved-to-last) axis; one-hot over
        # the class dim then sum over k -> 0/1 mask shaped like x
        oh = _jnp().sum(_jnn.one_hot(top_i if axis in (-1, x.ndim - 1)
                                     else jnp.moveaxis(top_i, axis, -1),
                                     x.shape[axis], dtype=x.dtype), axis=-2)
        if axis not in (-1, x.ndim - 1):
            oh = jnp.moveaxis(oh, -1, axis)
        return oh
    raise ValueError(ret_typ)


@register("shuffle", needs_rng=True, differentiable=False)
def shuffle(key, x):
    from jax import random as jr

    return jr.permutation(key, x, axis=0)


# ==========================================================================
# linalg namespace (reference: src/operator/tensor/la_op.cc)
# ==========================================================================
@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    jnp = _jnp()
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    jnp = _jnp()
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(A):
    return _jnp().linalg.cholesky(A)


@register("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    from jax.scipy.linalg import solve_triangular

    a = A
    if transpose:
        a = _jnp().swapaxes(a, -1, -2)
        lower = not lower
    if rightside:
        x = solve_triangular(a.swapaxes(-1, -2), (alpha * B).swapaxes(-1, -2),
                             lower=not lower)
        return x.swapaxes(-1, -2)
    return solve_triangular(a, alpha * B, lower=lower)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    jnp = _jnp()
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    jnp = _jnp()
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("linalg_det")
def linalg_det(A):
    return _jnp().linalg.det(A)


@register("linalg_inverse")
def linalg_inverse(A):
    return _jnp().linalg.inv(A)


@register("linalg_svd", nout=3)
def linalg_svd(A):
    jnp = _jnp()
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt


# ==========================================================================
# misc
# ==========================================================================
@register("histogram", differentiable=False, nout=2)
def histogram(x, bin_cnt=10, range=None):
    jnp = _jnp()
    lo, hi = range if range is not None else (float(_np.asarray(x).min()),
                                              float(_np.asarray(x).max()))
    cnt, edges = jnp.histogram(x, bins=int(bin_cnt), range=(lo, hi))
    return cnt.astype(_np.float32), edges


@register("amp_multicast", nout="dynamic")
def amp_multicast(*arrays, num_outputs=None):
    jnp = _jnp()
    # cast all to widest dtype among inputs (reference: amp_multicast)
    widest = _np.result_type(*[_np.dtype(a.dtype) if a.dtype != jnp.bfloat16 else _np.float32 for a in arrays])
    return tuple(a.astype(widest) for a in arrays)


# ==========================================================================
# misc late additions (reference: src/operator/tensor + contrib misc)
# ==========================================================================
@register("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    jnp = _jnp()
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("log_sigmoid")
def log_sigmoid(x):
    from jax import nn

    return nn.log_sigmoid(x)


@register("gelu")
def gelu_op(x):
    from jax import nn

    return nn.gelu(x, approximate=False)


@register("unravel_index", differentiable=False)
def unravel_index(x, shape=None):
    jnp = _jnp()
    idx = jnp.unravel_index(x.astype(_np.int64), shape)
    return jnp.stack(idx, axis=0)


@register("ravel_multi_index", differentiable=False)
def ravel_multi_index(x, shape=None):
    jnp = _jnp()
    strides = _np.concatenate([_np.cumprod(shape[::-1])[::-1][1:], [1]])
    return jnp.sum(x * jnp.asarray(strides)[:, None], axis=0)


@register("khatri_rao")
def khatri_rao(*mats):
    """Column-wise Kronecker product (reference:
    src/operator/contrib/krprod.cc)."""
    jnp = _jnp()
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return out


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new):
    """Copy rows of ``new`` into ``old`` at ``index`` (reference:
    src/operator/contrib/index_copy.cc)."""
    return old.at[index.astype(_np.int32)].set(new)


@register("_contrib_index_array", aliases=("index_array",),
          differentiable=False)
def index_array(data, axes=None):
    """Per-element N-D indices (reference: src/operator/contrib/index_array.cc)."""
    jnp = _jnp()
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    elif isinstance(axes, int):
        axes = (axes,)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
    sel = [grids[a] for a in axes]
    return jnp.stack(sel, axis=-1).astype(_np.int64)


@register("broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    """Broadcast lhs to rhs's shape (reference:
    src/operator/tensor/broadcast_reduce_op_value.cc broadcast_like)."""
    jnp = _jnp()
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    target = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        target[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(target))


@register("batch_take")
def batch_take(a, indices):
    """Per-row element pick: out[i] = a[i, indices[i]] (reference:
    src/operator/tensor/indexing_op.cc batch_take)."""
    jnp = _jnp()
    idx = indices.astype("int32").reshape(-1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("multi_sum_sq")
def multi_sum_sq(*arrays, num_arrays=None):
    """Sum of squares per input array (reference:
    src/operator/contrib/multi_sum_sq.cc — the global-norm building block
    for LAMB/clip_global_norm)."""
    jnp = _jnp()
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


@register("masked_softmax")
def masked_softmax(data, mask=None, axis=-1, temperature=1.0,
                   normalize=True):
    """Softmax with a boolean mask (reference:
    src/operator/nn/softmax.cc masked_softmax, 1.x)."""
    jnp = _jnp()
    z = data / temperature
    if mask is not None:
        z = jnp.where(mask != 0, z, -jnp.inf)
    z = z - jnp.max(jnp.where(jnp.isneginf(z), -1e30, z), axis=axis,
                    keepdims=True)
    e = jnp.exp(z)
    if mask is not None:
        e = jnp.where(mask != 0, e, 0.0)
    denom = jnp.sum(e, axis=axis, keepdims=True)
    return e / jnp.maximum(denom, 1e-30)


@register("digamma")
def digamma(x):
    from jax.scipy.special import digamma as _dg

    return _dg(x)
