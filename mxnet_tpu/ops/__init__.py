"""Operator table population.  Importing this package registers every op
family (reference: static registration of NNVM_REGISTER_OP at library load,
SURVEY.md §3.2)."""
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import image_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import flash_attention  # noqa: F401
from . import quantization_ops  # noqa: F401
from . import legacy_ops  # noqa: F401
from .registry import OP_TABLE, get_op, list_ops, register  # noqa: F401
