"""KVStore: the parameter-synchronization abstraction.

Reference: ``src/kvstore/`` + ``python/mxnet/kvstore.py`` (SURVEY.md §3.3,
§4.4): string-keyed Init/Push/Pull/PushPull with five flavors (local, device,
nccl, dist_sync, dist_device_sync, dist_async) over CPU reducers, NCCL, and
the ps-lite parameter server.

TPU-native mapping (SURVEY.md §6.8, the north star's ``dist_tpu_sync``):

- ``local`` / ``device`` / ``nccl``  →  single-process reduction: gradients
  from N devices are summed with jax ops (XLA issues the device-to-device
  copies; on a multi-chip host this is an ICI transfer exactly like the
  reference's P2P/NCCL path).
- ``dist_sync`` / ``dist_device_sync`` / ``dist_tpu_sync``  →  multi-host
  data parallelism over a ``jax.sharding.Mesh``: push+pull lowers to one
  ``psum`` over the 'dp' mesh axis (bucketed; rides ICI intra-slice, DCN
  across slices).  The worker/server/scheduler triangle of ps-lite is
  replaced by jax.distributed SPMD — see parallel/.
- ``update_on_kvstore``: the server-side-optimizer semantics are provided by
  attaching an optimizer via ``set_optimizer`` — locally the updater runs on
  the reduced gradient once (instead of once per device), matching the
  reference's semantics (§4.4 ApplyUpdates).

The string API (create/init/push/pull/pushpull/set_optimizer/
set_gradient_compression) is the compatibility surface and is kept intact.
"""
from __future__ import annotations

import pickle

import numpy as _np

from . import fault
from . import telemetry
from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray, invoke
from .ndarray import ndarray as _ndm

__all__ = ["KVStore", "create"]

_PUSH_BYTES = telemetry.counter(
    "mxnet_kvstore_push_bytes_total", "bytes pushed (post-reduce, per key)")
_PULL_BYTES = telemetry.counter(
    "mxnet_kvstore_pull_bytes_total", "bytes pulled (per output)")
_PUSH_OPS = telemetry.counter("mxnet_kvstore_push_ops_total", "push calls")
_PULL_OPS = telemetry.counter("mxnet_kvstore_pull_ops_total", "pull calls")


def _nd_nbytes(v):
    """Best-effort payload size of an NDArray/RowSparse value — shape and
    dtype reads never sync the device."""
    try:
        data = getattr(v, "data", None)   # RowSparseNDArray: count rows
        if data is not None and isinstance(data, NDArray):
            v = data
        return int(v.size) * _np.dtype(v.dtype).itemsize
    except Exception:
        return 0


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class _HostRowSparseTable:
    """Server-side host-resident weight for row-sparse keys.

    Reference: the dist server's ``DataHandleRowSparse``
    (``src/kvstore/kvstore_dist_server.h`` — SURVEY.md §3.3/§4.4) keeps the
    table server-side and moves only touched rows per push/pull.  The
    TPU-native equivalent: the table lives in HOST memory (the idiom for
    embedding tables larger than HBM); ``row_sparse_pull`` gathers rows on
    host and device_puts only those rows, and sparse pushes update only the
    gradient's rows through the optimizer's own kernels on row slices.
    ``bytes_h2d``/``bytes_d2h`` count actual host<->device row traffic so
    tests can assert it scales with touched rows, not table size.
    """

    def __init__(self, dense_np):
        self.table = _np.array(dense_np)      # full table, host memory
        self.state = None                     # host optimizer-state leaves
        self.sparse_pushes = 0
        self.bytes_h2d = 0
        self.bytes_d2h = 0


class KVStore:
    """Single-process KVStore covering local/device/nccl semantics."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._pending_host_state = {}
        # per-key traffic history, persisted across promote/demote cycles
        # (ADVICE r5 #1: promote/demote thrash).  _sparse_push_counts
        # survives a demote so a re-promoted key re-enters the
        # mixed-workload path; _dense_pushed gates row_sparse_pull
        # promotion for keys whose traffic has been dense.
        self._sparse_push_counts = {}
        self._dense_pushed = set()

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        from .ndarray.sparse import RowSparseNDArray

        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if isinstance(v, RowSparseNDArray):
                # row-sparse-initialized keys live server-side on host
                self._store[k] = _HostRowSparseTable(_np.asarray(v._get()))
            else:
                self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Reduce values (one per device) into the store buffer.
        Reference: KVStoreLocal::PushImpl -> CommDevice::Reduce.

        The entry guard is the host-side transport seam: a transient
        fault armed (or observed) here is retried with bounded backoff
        BEFORE any store/updater state mutates, so a retried push never
        double-applies an update; the network retry for the dist store
        lives one layer down at ``collectives.allreduce``."""
        from .ndarray.sparse import RowSparseNDArray

        fault.guard("kvstore.push")
        _PUSH_OPS.inc()

        keys, grouped = _group_key_value(key, value)
        for k, vals in zip(keys, grouped):
            reduced = _reduce(vals)
            _PUSH_BYTES.inc(_nd_nbytes(reduced))
            if not isinstance(reduced, RowSparseNDArray):
                self._dense_pushed.add(k)
            if (isinstance(reduced, RowSparseNDArray)
                    and self._updater is not None
                    and self._optimizer is not None
                    and self._compression is None
                    # only optimizers that DECLARE lazy semantics (sgd,
                    # adagrad, adam set lazy_update) take the host lazy
                    # path; others keep the densify-and-update fallback
                    and getattr(self._optimizer, "lazy_update", False)
                    and not getattr(self, "_sharded_update", False)):
                host = self._ensure_host_table(k)
                if host is not None:
                    self._sparse_lazy_update(k, host, reduced)
                    continue
            if isinstance(self._store.get(k), _HostRowSparseTable):
                host = self._store[k]
                if (self._updater is not None
                        and self._optimizer is not None
                        and self._compression is None
                        and not isinstance(reduced, RowSparseNDArray)
                        and host.sparse_pushes > 0
                        and not getattr(self, "_sharded_update", False)):
                    # dense gradient on a MIXED-workload host key: apply
                    # the optimizer over all rows in place — no demote, so
                    # host state survives sparse<->dense transitions
                    self._host_dense_update(k, host, reduced)
                    continue
                # purely-dense traffic (key was only promoted by a
                # row_sparse_pull), no updater, compression, or sharded:
                # demote back to the device-resident path, handing any
                # accumulated host state to the updater — dense training
                # must not pay full-table host round trips per step
                self._store[k] = self._demote(k)
            if self._compression is not None:
                reduced = self._compression.round_trip(reduced, key=k)
            if self._updater is not None:
                # update_on_kvstore: apply optimizer to stored weight
                self._updater(_key_int(k), reduced, self._store[k])
            else:
                self._store[k] = reduced

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to every output (≙ CommDevice::Broadcast).
        Entry guard: see ``push`` — same retry-before-mutation contract."""
        fault.guard("kvstore.pull")
        _PULL_OPS.inc()
        keys, grouped = _group_key_value(key, out)
        for k, outs in zip(keys, grouped):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            src = self._store[k]
            if isinstance(src, _HostRowSparseTable):
                src = self._materialize(k)
            for o in outs:
                o._set(src.as_in_context(o.context)._get().astype(o._get().dtype))
                _PULL_BYTES.inc(_nd_nbytes(o))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def _is_host_key(self, key):
        """True when ``key``'s stored value is a host-resident row-sparse
        table — bucketing callers (gluon.Trainer) must route such keys
        per-key: their traffic is touched rows, not a stable flat span."""
        return isinstance(self._store.get(str(key)), _HostRowSparseTable)

    def _discard_transient(self, key):
        """Drop a transient (gradient-bucket) key's stored value after
        its pull: the flat buffers would otherwise duplicate the model's
        entire dense-gradient footprint in device memory for the rest of
        the run (and a replan would strand old-plan buffers forever)."""
        k = str(key)
        self._store.pop(k, None)
        self._dense_pushed.discard(k)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference: the dist server's
        DataHandleRowSparse, src/kvstore/kvstore_dist_server.h — SURVEY.md
        §3.3/§4.4).  Host-resident keys gather rows on host and device_put
        only those rows: bytes moved scale with len(row_ids), not with the
        table size."""
        if row_ids is None:
            return self.pull(key, out, priority)
        outs = _as_list(out)
        rids = _as_list(row_ids)
        keys = _as_list(key)
        if len(keys) == 1 and len(outs) > 1:
            keys = keys * len(outs)
        for k, o, r in zip(keys, outs, rids):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            src = self._store[k]
            if not isinstance(src, _HostRowSparseTable) and \
                    not getattr(self, "_sharded_update", False) and \
                    (self._sparse_push_counts.get(k, 0) > 0
                     or k not in self._dense_pushed):
                # promote only keys whose traffic is actually row-sparse:
                # a key that has seen dense pushes and no sparse push stays
                # on the device-side take path — otherwise an alternating
                # dense-push/row_sparse_pull workload pays a full-table
                # D2H+H2D promote/demote round trip per step (ADVICE r5 #1)
                val = src._get()
                sh = getattr(val, "sharding", None)
                if sh is None or len(sh.device_set) <= 1:
                    # promote: from here on this key serves rows host-side
                    src = self._ensure_host_table(k)
            if isinstance(src, _HostRowSparseTable):
                import jax.numpy as jnp

                rid = _np.asarray(r._get() if isinstance(r, NDArray)
                                  else r).astype(_np.int64)
                rid = _np.clip(rid, 0, src.table.shape[0] - 1)
                rows = src.table[rid]             # host gather: O(rows)
                src.bytes_h2d += rows.nbytes
                nd_rows = NDArray._from_jax(
                    jnp.asarray(rows)).as_in_context(o.context)
                o._set(nd_rows._get().astype(o._get().dtype))
                continue
            src_val = src._get()
            sharding = getattr(src_val, "sharding", None)
            if sharding is not None and len(sharding.device_set) > 1:
                # after a sharded update the stored weight is a global array
                # over the whole mesh (multi-process or multi-device); it
                # cannot mix with the single-device row_ids inside one
                # computation.  Fully-replicated: one addressable shard IS
                # the value (no host round-trip of the whole table).
                if sharding.is_fully_replicated:
                    local = src_val.addressable_data(0)
                else:  # pragma: no cover - stored weights are replicated
                    local = _np.asarray(src_val)
                src = NDArray._from_jax(local, src.context)
            src_local = src.as_in_context(o.context)
            rows = invoke("take", [src_local, r], {"axis": 0, "mode": "clip"})
            o._set(rows._get().astype(o._get().dtype))

    # -- host-resident row-sparse machinery --------------------------------
    def _ensure_host_table(self, k):
        """Promote key ``k``'s stored weight to a host-resident table.
        Returns the table, or None if the key cannot be served host-side
        (weight is a multi-device sharded global array)."""
        cur = self._store[k]
        if isinstance(cur, _HostRowSparseTable):
            return cur
        val = cur._get()
        sharding = getattr(val, "sharding", None)
        if sharding is not None and len(sharding.device_set) > 1:
            return None
        host = _HostRowSparseTable(_np.asarray(val))  # one-time D2H
        # re-promoted keys keep their sparse-push history: the
        # mixed-workload dense path (push) can engage immediately instead
        # of demoting on the first dense gradient
        host.sparse_pushes = self._sparse_push_counts.get(k, 0)
        if k in self._pending_host_state:
            # state saved by save_optimizer_states before this key was
            # re-promoted in the restored process
            host.state = self._pending_host_state.pop(k)
        self._store[k] = host
        return host

    def _ensure_host_state(self, k, host, probe_nd):
        """Create (or adopt) the host-resident optimizer state for key
        ``k``: full-height numpy mirrors of every state leaf.  Dense state
        already accumulated in the Updater is adopted, so promotion does
        not silently reset momentum/adam moments."""
        if host.state is not None:
            return
        idx = _key_int(k)
        adopted = None
        if self._updater is not None and idx in getattr(
                self._updater, "states", {}):
            adopted = self._updater.states.pop(idx)
            self._updater.states_synced.pop(idx, None)
        if adopted is not None:
            leaves, treedef = _flatten_state(adopted)
            host.state = ([None if lv is None else
                           _np.array(_np.asarray(lv._get()))
                           for lv in leaves], treedef)
            return
        probe = self._optimizer.create_state_multi_precision(idx, probe_nd)
        leaves, treedef = _flatten_state(probe)
        host.state = ([None if lv is None else
                       _np.zeros((host.table.shape[0],)
                                 + tuple(_np.asarray(lv._get()).shape[1:]),
                                 _np.asarray(lv._get()).dtype)
                       for lv in leaves], treedef)

    def _demote(self, k):
        """Turn a host-resident key back into a device NDArray, handing
        accumulated host optimizer state to the Updater so it survives."""
        import jax.numpy as jnp

        host = self._store[k]
        if host.state is not None and self._updater is not None:
            leaves, treedef = host.state
            idx = _key_int(k)
            self._updater.states[idx] = _unflatten_state(
                [None if lv is None else NDArray._from_jax(jnp.asarray(lv))
                 for lv in leaves], treedef)
            self._updater.states_synced[idx] = True
        return NDArray._from_jax(jnp.asarray(host.table))

    def _host_dense_update(self, k, host, grad):
        """Dense gradient against a host-resident key: run the optimizer
        over all rows in place (one full-table round trip — unavoidable for
        a dense grad) keeping the host state authoritative."""
        import jax.numpy as jnp

        idx = _key_int(k)
        w_nd = NDArray._from_jax(jnp.asarray(host.table))
        host.bytes_h2d += host.table.nbytes
        self._ensure_host_state(k, host, w_nd)
        leaves, treedef = host.state
        state_nds = [None if lv is None else NDArray._from_jax(
            jnp.asarray(lv)) for lv in leaves]
        state = _unflatten_state(state_nds, treedef)
        self._optimizer.update_multi_precision(idx, w_nd, grad, state)
        host.table[...] = _np.asarray(w_nd._get())
        host.bytes_d2h += host.table.nbytes
        new_leaves, _ = _flatten_state(state)
        for lv, new in zip(leaves, new_leaves):
            if lv is not None and new is not None:
                lv[...] = _np.asarray(new._get())

    def _materialize(self, k, count=True):
        """Full-table host->device transfer (dense pull of a host key)."""
        import jax.numpy as jnp

        host = self._store[k]
        if count:
            host.bytes_h2d += host.table.nbytes
        return NDArray._from_jax(jnp.asarray(host.table))

    def _sparse_lazy_update(self, k, host, grad):
        """Server-side lazy update: run the optimizer's own dense kernels on
        the touched-row slices, so ONLY those rows (weight + state) move and
        change — every optimizer gets reference ``lazy_update`` semantics.
        Reference: kvstore_dist_server.h DataHandleRowSparse applying the
        sparse FComputeEx updates (SURVEY.md §4.4)."""
        import jax.numpy as jnp

        rows = _np.asarray(grad._rs_indices).astype(_np.int64)
        vals = _np.asarray(grad._rs_values)           # D2H: K rows
        host.bytes_d2h += vals.nbytes
        if rows.size == 0:
            return
        # merge duplicate rows (multi-device reduce may concatenate)
        uniq, inv = _np.unique(rows, return_inverse=True)
        if uniq.size != rows.size:
            merged = _np.zeros((uniq.size,) + vals.shape[1:], vals.dtype)
            _np.add.at(merged, inv, vals)
            rows, vals = uniq, merged
        idx = _key_int(k)
        host.sparse_pushes += 1
        self._sparse_push_counts[k] = self._sparse_push_counts.get(k, 0) + 1
        w_rows = host.table[rows]
        w_nd = NDArray._from_jax(jnp.asarray(w_rows))
        g_nd = NDArray._from_jax(jnp.asarray(vals))
        host.bytes_h2d += w_rows.nbytes + vals.nbytes
        opt = self._optimizer
        self._ensure_host_state(k, host, w_nd)
        leaves, treedef = host.state
        slice_leaves = [None if lv is None else
                        NDArray._from_jax(jnp.asarray(lv[rows]))
                        for lv in leaves]
        for lv in slice_leaves:
            if lv is not None:
                host.bytes_h2d += _np.asarray(lv._get()).nbytes
        state = _unflatten_state(slice_leaves, treedef)
        opt.update_multi_precision(idx, w_nd, g_nd, state)
        new_w = _np.asarray(w_nd._get())              # D2H: K rows back
        host.table[rows] = new_w
        host.bytes_d2h += new_w.nbytes
        new_leaves, _ = _flatten_state(state)
        for lv, new in zip(leaves, new_leaves):
            if lv is not None and new is not None:
                arr = _np.asarray(new._get())
                lv[rows] = arr
                host.bytes_d2h += arr.nbytes

    # -- optimizer attach ---------------------------------------------------
    def set_optimizer(self, optimizer):
        """Reference: kv.set_optimizer pickles the optimizer to the servers
        (§4.4).  Locally: build the updater in-process."""
        from . import optimizer as opt_mod

        # round-trip through pickle to preserve reference semantics
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        ctype = compression_params.get("type", "2bit")
        if ctype == "2bit":
            self._compression = TwoBitCompression(
                float(compression_params.get("threshold", 0.5)))
        elif ctype == "int8":
            # TPU-native EQuARX-style: quantization happens inside the
            # collective in the dist store; locally it is a round-trip
            self._compression = Int8Compression()
        else:
            raise MXNetError(f"unknown gradient compression type {ctype}")

    # -- optimizer state io --------------------------------------------------
    #
    # File format (two variants, discriminated by an explicit header —
    # never by speculative unpickling):
    #
    # 1. plain: the updater's own states blob, byte-for-byte (what the
    #    reference's mx.mod/Trainer save_optimizer_states writes) — used
    #    whenever no host-resident row-sparse keys hold server-side state.
    # 2. bundled: the 8-byte magic ``MXKVOPT1`` followed by a pickled
    #    ``{"updater": <plain blob>, "host_states": {key: state}}`` dict —
    #    host-resident row-sparse keys keep their optimizer state
    #    server-side and it must survive the round trip.  Since ZeRO-1
    #    (parallel/zero.py) the dict may also carry ``"zero"``: the
    #    engine's per-parameter sharded-state payload, dp- and
    #    plan-agnostic so a restore works onto a different dp size,
    #    bucket cap, or with MXNET_ZERO off (folded back into the
    #    replicated updater).  Old readers ignore unknown dict keys.
    #
    # The magic cannot collide with variant 1: updater blobs are pickle
    # streams and no pickle protocol starts with b"MXKV".  Readers that
    # predate the bundled format still load variant-1 files unchanged.
    _STATE_MAGIC = b"MXKVOPT1"

    def _optimizer_states_blob(self, dump_optimizer=False):
        """The bytes ``save_optimizer_states`` writes — exposed so async
        checkpointing can snapshot the state on the step loop's thread and
        hand the file I/O to a background writer."""
        if self._updater is None:
            raise MXNetError("no updater attached")
        blob = self._updater.get_states(dump_optimizer)
        host = {k: v.state for k, v in self._store.items()
                if isinstance(v, _HostRowSparseTable) and v.state is not None}
        zero = getattr(self, "_zero", None)
        zero_payload = zero.state_payload() \
            if zero is not None and zero.has_state else None
        if host or zero_payload is not None:
            bundle = {"updater": blob, "host_states": host}
            if zero_payload is not None:
                bundle["zero"] = zero_payload
            return self._STATE_MAGIC + pickle.dumps(bundle)
        return blob

    def save_optimizer_states(self, fname, dump_optimizer=False):
        blob = self._optimizer_states_blob(dump_optimizer)
        with open(fname, "wb") as f:
            f.write(blob)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater attached")
        with open(fname, "rb") as f:
            data = f.read()
        if data.startswith(self._STATE_MAGIC):
            obj = pickle.loads(data[len(self._STATE_MAGIC):])
            self._adopt_bundled_states(obj["updater"], obj["host_states"],
                                       obj.get("zero"))
            return
        # pre-MXKVOPT1 files only: one generation of bundled state shipped
        # as a bare pickled wrapper dict.  This is the sole remaining
        # sniff, scoped to that marker key; drop it when those files age out.
        try:
            legacy = pickle.loads(data)
        except Exception:
            legacy = None
        if isinstance(legacy, dict) and "__kv_host_states__" in legacy:
            self._adopt_bundled_states(legacy["updater"],
                                       legacy["__kv_host_states__"])
        else:
            self._updater.set_states(data)

    def _adopt_bundled_states(self, updater_blob, host_states,
                              zero_payload=None):
        from .parallel.distributed import ShardedOptimizerUpdater

        if zero_payload is not None and \
                isinstance(self._updater, ShardedOptimizerUpdater):
            # The bundle was written by a ZeRO-mode store: its updater
            # blob is the base Updater layout (bypass keys only — the
            # bucketed keys' state travels in zero_payload).  This store
            # runs the per-key sharded updater instead (MXNET_ZERO off on
            # a dist store), so fold BOTH parts into its flat sharded
            # per-key layout — the momentum buffers carry the same
            # lr-folded form on every path, so values transfer exactly.
            obj = pickle.loads(updater_blob)
            if isinstance(obj, tuple) and len(obj) == 2:
                states, self._updater.optimizer = obj
                self._optimizer = self._updater.optimizer
            else:
                states = obj
            self._updater.adopt_dense_states(states)
            self._updater.adopt_dense_states(zero_payload["members"])
            self._pending_host_state.update(host_states)
            for k in list(self._pending_host_state):
                cur = self._store.get(k)
                if isinstance(cur, _HostRowSparseTable):
                    cur.state = self._pending_host_state.pop(k)
            return
        self._updater.set_states(updater_blob)
        self._pending_host_state.update(host_states)
        for k in list(self._pending_host_state):
            cur = self._store.get(k)
            if isinstance(cur, _HostRowSparseTable):
                cur.state = self._pending_host_state.pop(k)
        if zero_payload is None:
            return
        zero = getattr(self, "_zero", None)
        if zero is not None:
            # shards re-flatten lazily at each bucket's next step —
            # valid for ANY dp size or bucket plan
            zero.load_state_payload(zero_payload)
        else:
            # ZeRO off (or unsupported) at restore time: fold the
            # sharded pieces back into the replicated updater so
            # momentum survives the mode switch
            from .parallel import zero as _zero

            _zero.fold_into_updater(self._updater, zero_payload)

    def barrier(self):
        _ndm.waitall()

    def _send_command_to_servers(self, head, body):
        pass


class TwoBitCompression:
    """2-bit stochastic gradient quantization with error-feedback residual.

    Reference: ``src/kvstore/gradient_compression.{cc,cu}`` (SURVEY.md §3.3).
    TPU-native: pure jax quantize/dequantize, XLA-fused; the residual rides
    in host state per key.
    """

    def __init__(self, threshold=0.5):
        self.threshold = threshold
        self._residuals = {}

    def drop_residuals(self, match):
        """Forget residual state for keys where ``match(key)`` is true —
        called when a transient (bucket) key will never be pushed again,
        so its flat residual array does not leak for the process's life."""
        for k in [k for k in self._residuals if match(k)]:
            del self._residuals[k]

    def round_trip(self, grad_nd, key=None):
        import jax.numpy as jnp

        g = grad_nd._get()
        r = self._residuals.get(key)
        if r is not None:
            g = g + r
        t = self.threshold
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0)).astype(g.dtype)
        self._residuals[key] = g - q
        return NDArray._from_jax(q, grad_nd.context)


class Int8Compression:
    """int8 symmetric quantization (PAPERS.md EQuARX).  In dist_tpu_sync
    the payload is quantized INSIDE the allreduce (allreduce_hosts_
    quantized: 4x less network traffic); in local stores this round-trip
    models the same error."""

    def round_trip(self, grad_nd, key=None):
        from .parallel.collectives import _int8_quantize

        import jax.numpy as jnp

        g = grad_nd._get()
        q, scale = _int8_quantize(g)
        return NDArray._from_jax(
            (q.astype(jnp.float32) * scale).astype(g.dtype),
            grad_nd.context)


class DistTPUSyncKVStore(KVStore):
    """``dist_tpu_sync``: multi-host data parallelism via mesh collectives.

    In a multi-process jax.distributed job every worker holds its local
    shard's gradients; push+pull is one bucketed psum over the 'dp' axis of
    the global mesh (parallel.mesh.get_default_mesh()).  In a single-process
    session it degrades to local semantics (matching how the reference's
    dist kvstore behaves with one worker).
    """

    def __init__(self, kind="dist_tpu_sync"):
        super().__init__(kind)
        self._mesh = None
        self._fuse_bucketer = None  # deterministic fusion plan cache
        self._zero = None           # ZeRO-1 bucketed sharded update
        self._zero_bucketer = None  # multi-key push plan cache
        self._zero_key_plans = {}   # per-key push: stable one-key plans

    @property
    def rank(self):
        import jax

        return jax.process_index()

    @property
    def num_workers(self):
        import jax

        return jax.process_count()

    def _get_mesh(self):
        if self._mesh is None:
            from .parallel import mesh as _mesh

            self._mesh = _mesh.get_default_mesh()
        return self._mesh

    def set_optimizer(self, optimizer):
        """update_on_kvstore distributed semantics (SURVEY.md §6.8): the
        server-side optimizer becomes a reduce-scatter + sharded-state update
        + all-gather over the device mesh.  ``MXNET_ZERO=1`` runs that
        recipe BUCKETED (parallel/zero.py: 2 collectives per flat bucket,
        optimizer state permanently sharded 1/dp per rank) instead of the
        per-key ShardedOptimizerUpdater (2 collectives per KEY).
        Optimizers without a jax-pure sharded implementation fall back to
        the replicated local updater (numerically identical, state not
        sharded)."""
        from .parallel import distributed as _dist
        from .parallel import zero as _zero

        super().set_optimizer(optimizer)
        self._zero = None
        self._sharded_update = False
        if _zero.zero_enabled() and _zero.supports(self._optimizer):
            self._zero = _zero.ZeroBucketEngine(self._optimizer)
            # a replicated checkpoint restored into ZeRO mode keeps its
            # momentum: bucket shards adopt the updater's per-key state
            self._zero.adopt = _zero.updater_adopter(self._updater)
        elif _dist.supports_sharded_update(self._optimizer):
            self._updater = _dist.ShardedOptimizerUpdater(self._optimizer)
            self._sharded_update = True

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray

        fault.guard("kvstore.push")
        _PUSH_OPS.inc()
        keys, grouped = _group_key_value(key, value)
        reduced_list = [_reduce(vals) for vals in grouped]
        for reduced in reduced_list:
            _PUSH_BYTES.inc(_nd_nbytes(reduced))
        # record dense traffic like the base store does: the inherited
        # row_sparse_pull promote gate reads _dense_pushed, and a key it
        # wrongly promotes would crash this push path (no host-table
        # branch here)
        for k, reduced in zip(keys, reduced_list):
            if not isinstance(reduced, RowSparseNDArray):
                self._dense_pushed.add(k)
            if isinstance(self._store.get(k), _HostRowSparseTable):
                # promoted by a row_sparse_pull that preceded the first
                # push (never-pushed keys pass the gate): demote back to
                # a device array, handing any host optimizer state to the
                # updater, before the dist update path runs
                self._store[k] = self._demote(k)
        # ZeRO-1 partition: dense float keys with a server-side optimizer
        # ride the bucketed reduce-scatter → sharded update → all-gather
        # (their cross-process sum happens INSIDE the reduce-scatter, so
        # they must not also ride the fused allreduce below); row-sparse
        # and host-promoted keys keep the per-key replicated bypass
        zero_set = set()
        if self._zero is not None and self._updater is not None:
            from .parallel import bucketing as _bucketing

            zero_set = {k for k, red in zip(keys, reduced_list)
                        if not isinstance(red, RowSparseNDArray)
                        and not isinstance(self._store.get(k),
                                           _HostRowSparseTable)
                        and _bucketing.float_kind(red.dtype)}
        if self.num_workers > 1 and not (
                getattr(self, "_sharded_update", False)
                and self._updater is not None):
            # ZeRO keys are excluded: their cross-process sum happens
            # inside the reduce-scatter.  The call stays unconditional
            # (an empty subset is a no-op) so every peer issues the same
            # collective sequence regardless of the partition.
            idxs = [j for j, k in enumerate(keys) if k not in zero_set]
            sub = self._allreduce_bucketed(
                [reduced_list[j] for j in idxs],
                [keys[j] for j in idxs])
            for j, v in zip(idxs, sub):
                reduced_list[j] = v
        # int8 compression happens INSIDE the bucketed collective; a host
        # round-trip afterwards would quantize the already-summed gradient
        # a second time
        already_compressed = (self.num_workers > 1
                              and isinstance(self._compression,
                                             Int8Compression)
                              and not (getattr(self, "_sharded_update",
                                               False)
                                       and self._updater is not None))
        zero_batch = []
        for k, reduced in zip(keys, reduced_list):
            if k in zero_set:
                # compression round-trips BEFORE the pack, like the
                # per-key sharded path (quantizing inside the
                # reduce-scatter itself is the EQuARX item's hook)
                if self._compression is not None:
                    reduced = self._compression.round_trip(reduced, key=k)
                zero_batch.append((k, reduced))
                continue
            if getattr(self, "_sharded_update", False) and \
                    self._updater is not None:
                # the sharded updater consumes the process-local reduced
                # gradient directly: the cross-process sum happens inside
                # its jit as the reduce-scatter input
                if self._compression is not None:
                    reduced = self._compression.round_trip(reduced, key=k)
                self._updater(_key_int(k), reduced, self._store[k])
                continue
            if self._compression is not None and not already_compressed:
                reduced = self._compression.round_trip(reduced, key=k)
            if self._updater is not None:
                self._updater(_key_int(k), reduced, self._store[k])
            else:
                self._store[k] = reduced
        if zero_batch:
            self._zero_push(zero_batch)

    def _zero_push(self, batch):
        """ZeRO-1 server-side update for a batch of ``(key, reduced)``
        dense float pairs: assign them to a deterministic bucket plan,
        then per bucket run reduce-scatter → this-rank's-shard optimizer
        update → all-gather (parallel/zero.py) and write the updated
        weights back into the store.

        Plan keying mirrors the PR 4 fusion cache, split by push shape:
        a multi-key push rides one shared :class:`bucketing.Bucketer`
        (its generation tags the engine state; a replan retires the old
        generation's shards so momentum re-flattens instead of aliasing
        a different bucket composition), while the common per-key push
        pattern (update_on_kvstore trainers pushing one key at a time)
        gets a stable per-key one-bucket plan — a shared planner would
        replan on every call and thrash shard state."""
        from .parallel import bucketing as _bucketing

        if len(batch) > 1:
            # a key switching from the per-key pattern hands its momentum
            # over through the harvest (one resident state per key, never
            # two independent shards double-advancing the update count)
            for k, _ in batch:
                old = self._zero_key_plans.pop(k, None)
                if old is not None:
                    self._zero.retire(("key", k, old[2]))
            entries = [(k, tuple(v.shape), str(v.dtype)) for k, v in batch]
            if self._zero_bucketer is None:
                self._zero_bucketer = _bucketing.Bucketer()
            plan = self._zero_bucketer.plan_for(entries)
            gen = self._zero_bucketer.generation
            prev = getattr(self, "_zero_gen_seen", None)
            if prev != gen:
                self._zero_gen_seen = gen
                if prev is not None:
                    self._zero.retire(("gen", prev))
            vals = dict(batch)
            for b in plan.buckets:
                self._zero_step_bucket(("gen", gen), b, vals)
            return
        k, reduced = batch[0]
        gen_seen = getattr(self, "_zero_gen_seen", None)
        if gen_seen is not None:
            # the symmetric hand-over: a multi-key generation is resident
            # and this key may be part of it — harvest it so the one-key
            # plan re-adopts the carried momentum (the next multi-key
            # push lazily re-assembles from the same carry)
            self._zero.retire(("gen", gen_seen))
            self._zero_gen_seen = None
        sig = (tuple(reduced.shape), str(reduced.dtype))
        entry = self._zero_key_plans.get(k)
        if entry is None or entry[0] != sig:
            version = 0
            if entry is not None:
                # shape/dtype change retires the old one-key plan like a
                # generation bump (state must never alias across layouts)
                version = entry[2] + 1
                self._zero.retire(("key", k, entry[2]))
            (bucket,) = _bucketing.assign_buckets(
                [(k, sig[0], sig[1])],
                cap_bytes=_bucketing.bucket_cap_bytes()).buckets
            entry = (sig, bucket, version)
            self._zero_key_plans[k] = entry
        self._zero_step_bucket(("key", k, entry[2]), entry[1],
                               {k: reduced})

    def _zero_step_bucket(self, tag, bucket, vals):
        from .parallel import bucketing as _bucketing

        flat = _bucketing.pack([vals[k]._get() for k in bucket.keys])
        w_flat = _bucketing.pack([self._store[k]._get()
                                  for k in bucket.keys])
        new_flat = self._zero.step_bucket(
            tag, bucket, [flat], w_flat,
            opt_keys=[_key_int(k) for k in bucket.keys])
        for k, part in zip(bucket.keys,
                           _bucketing.unpack(bucket, new_flat)):
            old = self._store[k]
            self._store[k] = NDArray._from_jax(
                part.astype(old._get().dtype), old.context)

    def load_optimizer_states(self, fname):
        super().load_optimizer_states(fname)
        if self._zero is not None:
            # a dump_optimizer blob replaced the updater's optimizer
            # object: the engine must advance THAT one (update counts /
            # Adam bias correction resume where the save left off)
            from .parallel import zero as _zero

            new_opt = self._updater.optimizer
            if _zero.kind_of(new_opt) != self._zero._kind:
                # the blob swapped the optimizer CLASS: the engine's
                # jitted bodies and state layout are kind-specific, so
                # rebinding alone would silently run the wrong math —
                # rebuild.  (A sharded payload of the old kind was
                # already rejected by load_state_payload's kind check;
                # the replicated per-key states the blob carries are
                # adopted into the new engine's shards at each bucket's
                # first step.)
                engine = None
                if _zero.supports(new_opt):
                    engine = _zero.ZeroBucketEngine(new_opt)
                    engine.adopt = _zero.updater_adopter(self._updater)
                self._zero = engine
                self._zero_bucketer = None
                self._zero_key_plans = {}
                self._zero_gen_seen = None
            else:
                self._zero.optimizer = new_opt
            self._optimizer = new_opt

    def _allreduce_bucketed(self, nds, keys=None):
        """Cross-host allreduce: jax makes a global array over the dp mesh
        and psums it (rides ICI within a slice, DCN across slices).

        Fusion (parallel/bucketing.py): values under
        MXNET_KVSTORE_BIGARRAY_BOUND elements ride dtype-segregated flat
        buckets capped at MXNET_ALLREDUCE_BUCKET_MB (deterministic
        assignment in push order, cached across steps — every SPMD peer
        issues the identical collective sequence); larger values — and
        everything when the cap is 0 — get their own collective.  The
        per-key ``kvstore_push_bytes`` accounting happened in ``push``;
        fused flat-buffer bytes are counted ONCE per bucket in the
        separate ``mxnet_allreduce_bucket_*`` families, never re-added to
        the push counter."""
        from . import env
        from .parallel import bucketing as _bucketing
        from .parallel.collectives import allreduce_hosts

        bound = env.kvstore_bigarray_bound()
        cap = _bucketing.bucket_cap_bytes()
        int8 = isinstance(self._compression, Int8Compression)
        reduce_fn = allreduce_hosts
        if int8:
            # quantize inside the collective; fused buckets keep a
            # PER-TENSOR scale so small-magnitude grads keep resolution
            from .parallel.collectives import allreduce_hosts_quantized

            reduce_fn = allreduce_hosts_quantized
        vals = [nd._get() for nd in nds]
        out = list(vals)
        done = set()
        small = [i for i, v in enumerate(vals) if v.size <= bound] \
            if cap > 0 else []
        # a single small value can never fuse: skip the planner entirely,
        # or the common per-key push pattern (update_on_kvstore trainers)
        # would thrash the one-slot plan cache on every call
        if len(small) > 1:
            entries = [(keys[i] if keys is not None else i,
                        tuple(vals[i].shape), str(vals[i].dtype))
                       for i in small]
            if self._fuse_bucketer is None:
                self._fuse_bucketer = _bucketing.Bucketer()
            plan = self._fuse_bucketer.plan_for(entries)
            pos = {e[0]: i for e, i in zip(entries, small)}
            for b in plan.buckets:
                if not b.fused:
                    continue  # singleton: per-value collective below
                members = [pos[k] for k in b.keys]
                if int8:
                    from .parallel.collectives import (
                        allreduce_hosts_quantized_multi)

                    fused = allreduce_hosts_quantized_multi(
                        [vals[i] for i in members])
                    for i, v in zip(members, fused):
                        out[i] = v
                else:
                    flat = _bucketing.pack([vals[i] for i in members])
                    summed = reduce_fn(flat)
                    for i, part in zip(members,
                                       _bucketing.unpack(b, summed)):
                        out[i] = part
                _bucketing.record_fused(b.nbytes)
                done.update(members)
        for i in range(len(vals)):
            if i not in done:
                out[i] = reduce_fn(vals[i])
        return [NDArray._from_jax(v, nd.context)
                for v, nd in zip(out, nds)]


_KVSTORE_REG = Registry("kvstore")
for _name in ("local", "device", "nccl", "local_allreduce_cpu",
              "local_allreduce_device"):
    _KVSTORE_REG.register(KVStore, name=_name)
for _name in ("dist_sync", "dist_device_sync", "dist_async", "dist_tpu_sync",
              "dist_sync_device", "dist"):
    _KVSTORE_REG.register(DistTPUSyncKVStore, name=_name)


def create(name="local"):
    """Create a KVStore by type string (reference: KVStore::Create,
    src/kvstore/kvstore.cc)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    store = _KVSTORE_REG.create(name, name)
    return store


# --------------------------------------------------------------------------
def _key_value(key, value):
    keys = _as_list(key)
    values = _as_list(value)
    if len(keys) == 1 and len(values) > 1:
        raise MXNetError("single key with multiple values: use push for "
                         "multi-device values")
    return [str(k) for k in keys], values


def _group_key_value(key, value):
    """Group (possibly multi-device) values per key (reference:
    KVStoreLocal::GroupKVPairs)."""
    keys = [str(k) for k in _as_list(key)]
    values = _as_list(value)
    if len(keys) == 1:
        return keys, [values]
    if len(values) == len(keys):
        return keys, [[v] if not isinstance(v, (list, tuple)) else list(v)
                      for v in values]
    if len(values) % len(keys) == 0:
        per = len(values) // len(keys)
        return keys, [values[i * per:(i + 1) * per] for i in range(len(keys))]
    raise MXNetError("cannot group keys with values")


def _reduce(vals):
    from .ndarray.sparse import RowSparseNDArray, add_rowsparse

    if all(isinstance(v, RowSparseNDArray) for v in vals):
        # sparse reduce keeps row_sparse storage: only touched rows move
        # (reference: CommCPU rsp reduce / kvstore_dist row_sparse push)
        if len(vals) == 1:
            # copy to match the dense path: the stored value must not alias
            # the caller's gradient array
            return vals[0].copy()
        acc = vals[0]
        for v in vals[1:]:
            acc = add_rowsparse(acc, v)
        return acc
    if len(vals) == 1:
        return vals[0].copy()
    ctx = vals[0].context
    acc = vals[0]._get()
    for v in vals[1:]:
        acc = acc + v.as_in_context(ctx)._get()
    return NDArray._from_jax(acc, ctx)


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _flatten_state(state):
    """Flatten an optimizer state (None / NDArray / tuple / list) into
    (leaves, treedef) so host mirrors can shadow each leaf."""
    if state is None:
        return [None], "none"
    if isinstance(state, (tuple, list)):
        return list(state), ("seq", isinstance(state, tuple), len(state))
    return [state], "single"


def _unflatten_state(leaves, treedef):
    if treedef == "none":
        return None
    if treedef == "single":
        return leaves[0]
    _, is_tuple, n = treedef
    seq = list(leaves[:n])
    return tuple(seq) if is_tuple else seq
