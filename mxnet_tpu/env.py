"""MXNET_* environment-variable behavior layer.

Reference: ``docs/static_site/src/pages/api/faq/env_var.md`` + the scattered
``dmlc::GetEnv`` reads in src/ (SURVEY.md §6.6 "Config/flags").  The
reference configures its engine/executor/kvstore through ~60 MXNET_* vars;
the TPU build keeps the same names for the vars whose concern still exists,
maps each to the XLA-native mechanism, and documents the ones XLA subsumes
instead of silently ignoring them.

Wired vars (read at ``import mxnet_tpu``):

- ``MXNET_ENGINE_TYPE``: ``NaiveEngine`` = eager op-by-op determinism
  switch (jax_disable_jit) — see :mod:`mxnet_tpu.engine`.
- ``MXNET_TPU_MATMUL_PRECISION``: fp32 matmul/conv MXU precision policy —
  see :mod:`mxnet_tpu.engine`.
- ``MXNET_SEED``: seeds the global RNG (≙ reference mx.random.seed at
  process start).
- ``MXNET_CPU_WORKER_NTHREADS``: default decode/augment pool width for
  ImageRecordIter and the Gluon DataLoader prefetcher (≙ the reference's
  OMP worker pool size).
- ``MXNET_PROFILER_AUTOSTART``: start the profiler with profile_all=True
  at import (≙ reference profiler autostart).
- ``MXNET_KVSTORE_BIGARRAY_BOUND``: size threshold (elements) above which
  dist kvstore values get their own collective rather than riding a fused
  bucket.
- ``MXNET_COORDINATOR_ADDRESS``: jax.distributed coordinator override
  (read in parallel.distributed.init).
- ``MXNET_TEST_TPU``: selects the real-chip test lane (tests/conftest.py).
- ``MXNET_EAGER_JIT``: eager jit-cache fast path on the imperative dispatch
  seam (default 1; see ndarray/dispatch_cache.py, ≙ the reference's
  CachedOp amortization of per-op launch cost).
- ``MXNET_EAGER_JIT_CACHE_SIZE``: executable LRU capacity (default 1024).
- ``MXNET_MP_START_METHOD``: DataLoader process-worker start method
  (default ``spawn``; ``fork`` is an explicit opt-in — the parent is
  always multi-threaded and fork can deadlock children on inherited
  locks).
- ``MXNET_BENCH_FORCE_SWEEP``: run the TPU-gated bench sweep branches
  (resnet config sweep, flash-block grid) on CPU too, so the sweep and
  headline-selection code paths are exercised before first chip contact.
- ``MXNET_FAULT_SPEC``: deterministic fault injection —
  ``<seam>:fail[:times[:Error]]``, comma-separated (e.g.
  ``checkpoint.write:fail:2``); see :mod:`mxnet_tpu.fault` for the seam
  list.  Read lazily at the first seam check so spawned DataLoader
  workers inherit it.
- ``MXNET_FAULT_MAX_RETRIES``: bounded retry budget for transient errors
  at the hardened seams (kvstore push/pull, host collectives,
  distributed.init; default 3).
- ``MXNET_FAULT_BACKOFF_MS``: first-retry backoff seed in ms (doubles per
  retry, full jitter, 30s cap; default 100).  Also seeds the
  between-restart backoff of ``checkpoint.run_with_recovery``.
- ``MXNET_TELEMETRY_PORT``: opt-in background HTTP telemetry endpoint
  (``/metrics`` Prometheus text, ``/snapshot`` JSON, ``/healthz``) on
  127.0.0.1:<port>, started at import.  Unset/0 = no server (metric
  RECORDING is always on and costs nothing on the op hot path — see
  :mod:`mxnet_tpu.telemetry`).
- ``MXNET_TELEMETRY_TIMELINE_STEPS``: step-timeline ring capacity
  (completed per-step phase records kept for snapshot(); default 256).
- ``MXNET_TELEMETRY_COMPILE_EVENTS``: compile-event ring capacity
  (fresh jax.jit traces kept with elapsed + cause; default 512).
- ``MXNET_TELEMETRY_AGG_EVERY``: cross-rank telemetry aggregation
  stride — every N-th step-boundary tick each rank publishes its
  snapshot to ``MXNET_TELEMETRY_AGG_DIR`` and rank 0 merges the peers'
  into rank-labeled families + per-phase skew histograms (default 0 =
  off; pure host-side file IO, never a device collective — see
  :mod:`mxnet_tpu.telemetry_agg`).
- ``MXNET_TELEMETRY_AGG_DIR``: the shared directory those per-rank
  snapshot files live in (unset = aggregation off).
- ``MXNET_TELEMETRY_AGG_TRANSPORT``: snapshot-gather transport for the
  cross-rank aggregator — ``file`` (default; the shared-directory
  gather above) or ``kv`` (the jax.distributed KV store, for pods
  without a shared filesystem).  Black-box crash dumps stay file-based
  either way: the distributed runtime is presumed dead when they are
  written.
- ``MXNET_FLIGHT_RECORDER``: the distributed flight recorder — an
  always-on preallocated ring stamping every collective issue site
  with a per-rank sequence number + tag digest, plus step/fault/
  compile/lifecycle context events (default 1; see
  :mod:`mxnet_tpu.flight_recorder` and README "Observability").
- ``MXNET_FLIGHT_RECORDER_CAP``: flight-recorder ring capacity in
  events (default 4096).
- ``MXNET_FLIGHT_DIR``: directory for ``blackbox.rank<N>.json`` crash
  dumps (default = ``MXNET_TELEMETRY_AGG_DIR``; with neither set the
  dumps are skipped).
- ``MXNET_TUNE``: the autotuning warm path — resolve knob values from
  the persistent tuning DB when a ``bench.py --tune`` run stored a
  winner for this signature/device/jax fingerprint (default 0; the
  warm path only ever REPLAYS, online exploration stays off — see
  :mod:`mxnet_tpu.tuning`).  Explicit env pins always beat the DB.
- ``MXNET_TUNE_DB_DIR``: directory for the persistent tuning DB the
  warm path reads and ``bench.py --tune`` writes (unset = no DB, the
  warm path resolves defaults even with ``MXNET_TUNE=1``).
- ``MXNET_LEDGER_SKEW_THRESHOLD``: cross-rank collective-ledger
  position divergence (max - min of
  ``mxnet_collective_ledger_position`` at a merge) that arms the
  pre-hang alert; sustained for ``MXNET_LEDGER_SKEW_WINDOWS``
  consecutive aggregation merges it fires one lifecycle alert per
  episode (default 0 = off; same SLO-hook pattern as the goodput
  breach — see :mod:`mxnet_tpu.telemetry_agg`).
- ``MXNET_LEDGER_SKEW_WINDOWS``: consecutive above-threshold merges
  before the ledger-skew alert fires (default 3).
- ``MXNET_GOODPUT_SLO``: goodput-ratio SLO in [0, 1] — when the
  per-window (per completed step) productive ratio stays below it for
  ``MXNET_GOODPUT_SLO_WINDOWS`` consecutive windows, a lifecycle
  alert event fires and ``mxnet_goodput_slo_breaches_total``
  increments (default 0 = off).
- ``MXNET_GOODPUT_SLO_WINDOWS``: consecutive below-SLO windows before
  the alert fires (default 3).
- ``MXNET_TRACE_REQUESTS``: per-request serving span traces (queue wait
  → prefill → per-decode-step → sample → finish; default 1 — see
  :mod:`mxnet_tpu.serving.tracing` and the ``/v1/requests`` route).
- ``MXNET_TRACE_KEEP_SLOWEST``: tail-based retention — the N slowest
  completed request traces are always kept (default 16; error/evicted
  traces are kept regardless).
- ``MXNET_DEVICE_PEAK_FLOPS``: per-device peak FLOP/s override for the
  online MFU gauge (default 0 = TPU device-kind table; unknown peak =
  the gauge stays absent — see :mod:`mxnet_tpu.introspection`).
- ``MXNET_PREFETCH_BUFFER``: device-prefetch queue depth for
  ``DataLoader(prefetch_to_device=...)`` / ``TrainStep.run`` (default 2;
  0 disables the background pipeline — see gluon/data/prefetcher.py).
- ``MXNET_ALLREDUCE_BUCKET_MB``: gradient-bucket size cap in MiB for the
  fused allreduce path (default 32; 0 disables fusion and every key gets
  its own collective — see parallel/bucketing.py).
- ``MXNET_ZERO``: ZeRO-1 optimizer-state sharding on the bucketed
  dense-grad path (default 0 = replicated optimizer state).  Each flat
  grad bucket becomes reduce-scatter → this-rank's-shard optimizer
  update → all-gather, with momentum/Adam moments permanently sharded
  1/dp per rank — see :mod:`mxnet_tpu.parallel.zero`.  Requires
  bucketing on (``MXNET_ALLREDUCE_BUCKET_MB`` > 0) and an optimizer
  with a flat sharded update (SGD/Adam); everything else falls back to
  the replicated path per key.
- ``MXNET_CHECKPOINT_ASYNC``: default for ``CheckpointManager.save``'s
  ``async_`` parameter (0/unset = synchronous saves; explicit
  ``async_=`` always wins).
- ``MXNET_WATCHDOG_TIMEOUT_S``: per-step stall deadline in seconds for the
  lifecycle watchdog (default 0 = off; ``env.apply_env`` starts the
  watchdog when set — see :mod:`mxnet_tpu.lifecycle`).
- ``MXNET_WATCHDOG_ABORT``: whether a tripped watchdog exits the process
  (status ``lifecycle.EXIT_STALLED``) after writing the diagnosis file
  (default 1; 0 = diagnose only).
- ``MXNET_WATCHDOG_DIR``: directory for watchdog stall-diagnosis files
  (default the working directory).
- ``MXNET_GRACE_PERIOD_S``: seconds between a preemption signal and a
  forced exit when the training loop has not honored the stop (default
  0 = no forced exit; match the scheduler's SIGTERM→SIGKILL grace).
- ``MXNET_PREEMPTION_CHECKPOINT``: publish a final synchronous checkpoint
  on a graceful preemption stop (default 1).
- ``MXNET_LIFECYCLE_SIGNALS``: ``parallel.distributed.init`` installs the
  graceful SIGTERM/SIGINT handlers for multi-process jobs (default 1;
  0 = the embedder owns signal dispositions).
- ``MXNET_STOP_SYNC_EVERY``: issue the multi-process stop-agreement
  collective every N-th ``lifecycle.check_stop()`` call (default 1;
  larger N amortizes the per-step scalar all-reduce, stop latency grows
  to at most N steps).
- ``MXNET_SERVING_PORT``: default port for ``serving.serve``'s HTTP
  endpoint (the inference routes mount beside the telemetry
  ``/metrics`` on one 127.0.0.1 server; 0/unset = pick a free port).
- ``MXNET_SERVING_MAX_BATCH``: decode-batch admission cap for the
  serving engine (default 8; must fit the largest batch bucket).
- ``MXNET_SERVING_BATCH_BUCKETS``: comma-separated decode batch-size
  buckets the engine AOT-compiles (default ``1,2,4,8``; active rows pad
  up to the nearest bucket so every step hits a compiled signature).
- ``MXNET_SERVING_PREFILL_BUCKETS``: comma-separated prompt-length
  buckets for the prefill executable (default ``32,64,128``; prompts
  right-pad up — causal attention keeps real-position logits exact).
- ``MXNET_SERVING_QUEUE``: admission-queue bound (default 64; a full
  queue rejects with a clean backpressure error, HTTP 429).
- ``MXNET_SERVING_KV_PAGES``: KV-cache pool size in pages (default 512;
  page 0 is the reserved scratch page — see serving/kvcache.py).
- ``MXNET_SERVING_PAGE_SIZE``: tokens per KV page (default 16).
- ``MXNET_SERVING_DEADLINE_MS``: default per-request deadline in ms
  covering queueing + generation (default 0 = none; per-request
  ``deadline_ms`` overrides).
- ``MXNET_FLEET_REPLICAS``: serving-fleet replica count behind the
  router (default 2; ``serving.fleet.serve_fleet`` spawns this many
  real engine processes — see :mod:`mxnet_tpu.serving.fleet`).
- ``MXNET_FLEET_HEDGE_MS``: floor in ms for the hedged-duplicate delay
  (default 50; the effective delay is max(this, observed p99 dispatch
  latency) — a slow replica gets one duplicate on a peer, first winner
  cancels the loser by request id).
- ``MXNET_FLEET_RETRY_BUDGET``: per-request transient-retry budget for
  router→replica dispatch (default 2; rides the fault.py
  ``call_with_retries`` policy with full-jitter backoff).
- ``MXNET_FLEET_PROBE_INTERVAL_MS``: router health-probe period in ms
  (default 250; a SIGKILLed replica is detected within ~4 missed
  probes, well under the 1s detection budget).
- ``MXNET_FLEET_EJECT_THRESHOLD``: consecutive dispatch/probe failures
  before the circuit breaker ejects a replica (default 3; re-admission
  goes through bounded half-open probe traffic).
- ``MXNET_PLANNER_MESH``: default mesh for the sharding planner
  (``auto`` or an explicit ``dp=4,tp=2`` spec — see
  :mod:`mxnet_tpu.parallel.planner`).
- ``MXNET_PLANNER_HBM_GB``: per-device HBM budget in GiB the planner's
  auto mesh selection plans against (default 16.0; config, not probed,
  so every SPMD peer selects the same mesh).
- ``MXNET_PLANNER_PIPELINE_IN_JIT``: feed traced pipeline stage params
  into shard_map with ``P(pp)`` in_specs instead of the jax-0.4.37
  GSPMD replicated workaround (default 0; the ROADMAP "re-test after
  jax upgrade" item is now this one flag).
- ``MXNET_PLANNER_REPORT``: print the planner's ``visualize_sharding``
  report whenever a plan is computed (default 0).
- ``MXNET_GRAPH_PIPELINE``: graph-compiler pass pipeline between the
  traced (hybridized) graph and jit lowering (default 1; see
  :mod:`mxnet_tpu.graph` and README "Graph compiler").  0 = every
  consumer runs the raw traced program.
- ``MXNET_GRAPH_PASSES``: comma-separated graph-pass selection; plain
  names replace the default list, ``-name`` entries subtract from it
  (unset = the default catalog).
- ``MXNET_GRAPH_FUSE_CAP``: max ops per fused elementwise chain in the
  ``fuse_elemwise_chains`` pass (default 16; < 2 disables fusion).
- ``MXNET_SUBGRAPH_BACKEND``: subgraph backend applied automatically at
  Module bind time (see :mod:`mxnet_tpu.subgraph`; the backends are
  sugar over the graph-compiler pipeline; unset = none).
- ``MXNET_RESHARD_INFLIGHT_MB``: in-flight byte budget per live
  resharding transfer round (default 64 MiB; the arXiv:2112.01075
  memory bound — see :mod:`mxnet_tpu.parallel.resharding`).
- ``MXNET_COMPILE_CACHE``: persistent warm-start compile-cache gate
  (default 1; a cache additionally needs a directory — see
  :mod:`mxnet_tpu.compile_cache`).
- ``MXNET_COMPILE_CACHE_DIR``: directory for the session-default
  compile cache (unset = only the per-checkpoint-dir caches exist).
- ``MXNET_COMPILE_CACHE_SALT``: manual compile-cache invalidation key
  component (bump when Python-side semantics change under an unchanged
  signature).
- ``MXNET_NUM_WORKERS``: launcher-provided world size for
  ``parallel.distributed.init`` (``DMLC_NUM_WORKER`` is the legacy
  alias; default 1 = single process).
- ``MXNET_WORKER_ID``: launcher-provided rank for
  ``parallel.distributed.init`` and the checkpoint manager's
  primary-election sweep (``DMLC_WORKER_ID`` is the legacy alias).
  Read from the LAUNCHER env on purpose — rank must be knowable before
  the jax backend initializes.

Accepted-but-subsumed (XLA owns the concern; reads return the default and
``describe()`` says why):

- ``MXNET_EXEC_BULK_EXEC_TRAIN`` / ``MXNET_EXEC_BULK_EXEC_INFERENCE`` /
  ``MXNET_EXEC_ENABLE_INPLACE``: operator bulking/fusion/in-place planning
  is XLA's fusion + buffer-assignment pass.
- ``MXNET_ENFORCE_DETERMINISM``: XLA:TPU kernels are deterministic by
  construction (no atomics-race reductions); the switch therefore asserts
  rather than changes behavior.
- ``MXNET_GPU_MEM_POOL_RESERVE``: HBM pooling is the XLA allocator's
  (``XLA_PYTHON_CLIENT_MEM_FRACTION`` controls the reservation).
"""
from __future__ import annotations

import os

__all__ = ["get_int", "get_str", "get_bool", "cpu_worker_nthreads",
           "kvstore_bigarray_bound", "describe", "apply_env"]

_SUBSUMED = {
    "MXNET_EXEC_BULK_EXEC_TRAIN": "XLA fusion owns operator bulking",
    "MXNET_EXEC_BULK_EXEC_INFERENCE": "XLA fusion owns operator bulking",
    "MXNET_EXEC_ENABLE_INPLACE": "XLA buffer assignment owns in-place",
    "MXNET_ENFORCE_DETERMINISM": "XLA:TPU kernels are deterministic",
    "MXNET_GPU_MEM_POOL_RESERVE":
        "XLA allocator owns HBM pooling (XLA_PYTHON_CLIENT_MEM_FRACTION)",
}


def get_str(name, default=None):
    return os.environ.get(name, default)


def get_int(name, default=0):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        import warnings

        warnings.warn(f"{name}={v!r} is not an integer; using {default}",
                      stacklevel=2)
        return default


def get_bool(name, default=False):
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def cpu_worker_nthreads():
    """Default worker-pool width for decode/augment stages
    (reference: MXNET_CPU_WORKER_NTHREADS, default 1 there — default 4
    here since the TPU input pipeline assumes a threaded decode stage)."""
    return max(1, get_int("MXNET_CPU_WORKER_NTHREADS", 4))


def kvstore_bigarray_bound():
    """Elements above which a kvstore value gets its own collective
    (reference: MXNET_KVSTORE_BIGARRAY_BOUND, default 1e6)."""
    return get_int("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000)


def prefetch_buffer():
    """Device-prefetch queue depth (MXNET_PREFETCH_BUFFER, default 2;
    0 disables the background prefetch pipeline)."""
    return max(0, get_int("MXNET_PREFETCH_BUFFER", 2))


def allreduce_bucket_mb():
    """Fused-allreduce gradient-bucket cap in MiB
    (MXNET_ALLREDUCE_BUCKET_MB, default 32; 0 disables fusion)."""
    return max(0, get_int("MXNET_ALLREDUCE_BUCKET_MB", 32))


def zero_enabled():
    """ZeRO-1 optimizer-state sharding on the bucketed grad path
    (MXNET_ZERO, default off; parallel/zero.py)."""
    return get_bool("MXNET_ZERO", False)


def checkpoint_async_default():
    """Default for CheckpointManager.save(async_=None)
    (MXNET_CHECKPOINT_ASYNC, default off)."""
    return get_bool("MXNET_CHECKPOINT_ASYNC", False)


def get_float(name, default=0.0):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        import warnings

        warnings.warn(f"{name}={v!r} is not a number; using {default}",
                      stacklevel=2)
        return default


def watchdog_timeout_s():
    """Per-step stall deadline for the lifecycle watchdog
    (MXNET_WATCHDOG_TIMEOUT_S, default 0 = watchdog off)."""
    return max(0.0, get_float("MXNET_WATCHDOG_TIMEOUT_S", 0.0))


def grace_period_s():
    """Signal→forced-exit deadline for graceful preemption
    (MXNET_GRACE_PERIOD_S, default 0 = no forced exit)."""
    return max(0.0, get_float("MXNET_GRACE_PERIOD_S", 0.0))


def preemption_checkpoint_default():
    """Whether a graceful preemption stop publishes a final synchronous
    checkpoint (MXNET_PREEMPTION_CHECKPOINT, default on)."""
    return get_bool("MXNET_PREEMPTION_CHECKPOINT", True)


def stop_sync_every():
    """Issue the multi-process stop-agreement collective every N-th
    check_stop() call (MXNET_STOP_SYNC_EVERY, default 1 = every step
    boundary; raise to amortize on very short steps — stop latency grows
    to at most N steps)."""
    return max(1, get_int("MXNET_STOP_SYNC_EVERY", 1))


def serving_port():
    """Default port for serving.serve's HTTP endpoint
    (MXNET_SERVING_PORT, default 0 = pick a free port)."""
    return max(0, get_int("MXNET_SERVING_PORT", 0))


def serving_max_batch():
    """Serving decode-batch admission cap (MXNET_SERVING_MAX_BATCH,
    default 8)."""
    return max(1, get_int("MXNET_SERVING_MAX_BATCH", 8))


def serving_batch_buckets():
    """Decode batch-size bucket spec (MXNET_SERVING_BATCH_BUCKETS,
    default "1,2,4,8")."""
    return get_str("MXNET_SERVING_BATCH_BUCKETS", "1,2,4,8")


def serving_prefill_buckets():
    """Prompt-length bucket spec (MXNET_SERVING_PREFILL_BUCKETS,
    default "32,64,128")."""
    return get_str("MXNET_SERVING_PREFILL_BUCKETS", "32,64,128")


def serving_queue_bound():
    """Serving admission-queue bound (MXNET_SERVING_QUEUE, default 64)."""
    return max(1, get_int("MXNET_SERVING_QUEUE", 64))


def serving_kv_pages():
    """KV-cache pool pages (MXNET_SERVING_KV_PAGES, default 512; page 0
    is the reserved scratch page)."""
    return max(2, get_int("MXNET_SERVING_KV_PAGES", 512))


def serving_page_size():
    """Tokens per KV-cache page (MXNET_SERVING_PAGE_SIZE, default 16)."""
    return max(1, get_int("MXNET_SERVING_PAGE_SIZE", 16))


def serving_deadline_ms():
    """Default per-request serving deadline in ms
    (MXNET_SERVING_DEADLINE_MS, default 0 = none)."""
    return max(0, get_int("MXNET_SERVING_DEADLINE_MS", 0))


def fleet_replicas():
    """Serving-fleet replica count behind the router
    (MXNET_FLEET_REPLICAS, default 2; serving/fleet)."""
    return max(1, get_int("MXNET_FLEET_REPLICAS", 2))


def fleet_hedge_ms():
    """Hedged-duplicate delay floor in ms (MXNET_FLEET_HEDGE_MS,
    default 50; the router hedges at max(floor, observed p99))."""
    return max(0, get_int("MXNET_FLEET_HEDGE_MS", 50))


def fleet_retry_budget():
    """Per-request transient-retry budget for router→replica dispatch
    (MXNET_FLEET_RETRY_BUDGET, default 2)."""
    return max(0, get_int("MXNET_FLEET_RETRY_BUDGET", 2))


def fleet_probe_interval_ms():
    """Router health-probe period in ms (MXNET_FLEET_PROBE_INTERVAL_MS,
    default 250 — four missed probes still detect a dead replica well
    inside the 1s budget)."""
    return max(10, get_int("MXNET_FLEET_PROBE_INTERVAL_MS", 250))


def fleet_eject_threshold():
    """Consecutive dispatch/probe failures before the circuit breaker
    ejects a replica (MXNET_FLEET_EJECT_THRESHOLD, default 3)."""
    return max(1, get_int("MXNET_FLEET_EJECT_THRESHOLD", 3))


def planner_mesh():
    """Default mesh for PlannerConfig(mesh=None): "auto" or an explicit
    "dp=4,tp=2"-style spec (MXNET_PLANNER_MESH, default auto;
    parallel/planner)."""
    return get_str("MXNET_PLANNER_MESH", "auto")


def planner_hbm_gb():
    """Per-device HBM budget in GiB for the planner's auto mesh
    selection (MXNET_PLANNER_HBM_GB, default 16.0 — a v5e-class chip;
    the budget is config, not probed, so every SPMD peer plans against
    the same number)."""
    v = get_float("MXNET_PLANNER_HBM_GB", 16.0)
    return v if v > 0 else 16.0


def planner_pipeline_in_jit():
    """Use P(pp) in_specs for traced pipeline stage params instead of
    the jax-0.4.37 GSPMD replicated workaround
    (MXNET_PLANNER_PIPELINE_IN_JIT, default 0 — flip after a jax
    upgrade proves the weight-stationary in-jit sharding correct; see
    parallel/pipeline_parallel.py)."""
    return get_bool("MXNET_PLANNER_PIPELINE_IN_JIT", False)


def planner_report():
    """Print the visualize_sharding report whenever a plan is computed
    (MXNET_PLANNER_REPORT, default 0)."""
    return get_bool("MXNET_PLANNER_REPORT", False)


def graph_pipeline():
    """Graph-compiler pass pipeline on the hybridize/TrainStep/serving
    trace seam (MXNET_GRAPH_PIPELINE, default on; mxnet_tpu/graph)."""
    return get_bool("MXNET_GRAPH_PIPELINE", True)


def graph_passes():
    """Graph-pass selection spec (MXNET_GRAPH_PASSES; unset = default
    catalog, "-name" subtracts — parsed by graph.selected_pass_names)."""
    return get_str("MXNET_GRAPH_PASSES", "")


def graph_fuse_cap():
    """Max ops per fused elementwise chain (MXNET_GRAPH_FUSE_CAP,
    default 16; < 2 disables the fusion pass)."""
    return get_int("MXNET_GRAPH_FUSE_CAP", 16)


def reshard_inflight_mb():
    """Bounded in-flight byte budget per live-resharding transfer
    round (MXNET_RESHARD_INFLIGHT_MB, default 64 MiB; see
    parallel/resharding.py — the arXiv:2112.01075 memory bound)."""
    return max(1, get_int("MXNET_RESHARD_INFLIGHT_MB", 64))


def compile_cache_enabled():
    """Whether the persistent warm-start compile cache may be used
    (MXNET_COMPILE_CACHE, default on; a cache still needs a directory —
    MXNET_COMPILE_CACHE_DIR or the one CheckpointManager keeps beside
    its checkpoints)."""
    return get_bool("MXNET_COMPILE_CACHE", True)


def compile_cache_dir():
    """Explicit directory for the session-default compile cache
    (MXNET_COMPILE_CACHE_DIR, unset = no session default; checkpoint
    managers still attach their own beside the checkpoint dir)."""
    return get_str("MXNET_COMPILE_CACHE_DIR")


def compile_cache_salt():
    """Extra cache-key component for manual invalidation
    (MXNET_COMPILE_CACHE_SALT, default empty — bump it when Python-side
    semantics change under an unchanged signature, e.g. a rewritten
    loss closure)."""
    return get_str("MXNET_COMPILE_CACHE_SALT", "") or ""


def launcher_rank():
    """Launcher-provided rank from MXNET_WORKER_ID / DMLC_WORKER_ID —
    the LAUNCHER env on purpose, never ``jax.process_index()``: rank
    must be knowable without initializing the jax backend (the PR 2
    checkpoint-primary-election precedent).  One implementation shared
    by the telemetry aggregator and the flight recorder, so a dump's
    rank filename and the snapshot's rank label can never disagree."""
    for name in ("MXNET_WORKER_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(name)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def launcher_world():
    """Launcher-provided world size (MXNET_NUM_WORKERS /
    DMLC_NUM_WORKER; default 1) — same backend-free contract as
    :func:`launcher_rank`."""
    for name in ("MXNET_NUM_WORKERS", "DMLC_NUM_WORKER"):
        v = os.environ.get(name)
        if v:
            try:
                return max(1, int(v))
            except ValueError:
                pass
    return 1


def telemetry_agg_every():
    """Cross-rank telemetry aggregation stride: publish/merge per-rank
    snapshots every N-th step-boundary tick (MXNET_TELEMETRY_AGG_EVERY,
    default 0 = aggregation off; mxnet_tpu/telemetry_agg.py)."""
    return max(0, get_int("MXNET_TELEMETRY_AGG_EVERY", 0))


def telemetry_agg_dir():
    """Shared directory for the per-rank snapshot files the cross-rank
    aggregator gathers (MXNET_TELEMETRY_AGG_DIR; required for
    aggregation — unset leaves it off even with a stride set)."""
    return get_str("MXNET_TELEMETRY_AGG_DIR")


def telemetry_agg_transport():
    """Cross-rank snapshot-gather transport: ``file`` (shared-dir
    gather, the default) or ``kv`` (jax.distributed KV store —
    MXNET_TELEMETRY_AGG_TRANSPORT; black-box dumps stay file-based
    regardless, the runtime is presumed dead when they are written)."""
    v = (get_str("MXNET_TELEMETRY_AGG_TRANSPORT", "file") or
         "file").strip().lower()
    return v if v in ("file", "kv") else "file"


def flight_recorder_enabled():
    """Distributed flight recorder gate (MXNET_FLIGHT_RECORDER,
    default on; mxnet_tpu/flight_recorder.py)."""
    return get_bool("MXNET_FLIGHT_RECORDER", True)


def flight_recorder_cap():
    """Flight-recorder ring capacity in events
    (MXNET_FLIGHT_RECORDER_CAP, default 4096)."""
    return max(8, get_int("MXNET_FLIGHT_RECORDER_CAP", 4096))


def flight_dir():
    """Directory for black-box crash dumps (MXNET_FLIGHT_DIR, default
    = MXNET_TELEMETRY_AGG_DIR — the same gather the telemetry
    aggregation uses; None when neither is set → dumps are skipped)."""
    return get_str("MXNET_FLIGHT_DIR") or telemetry_agg_dir()


def tune_enabled():
    """Autotuning warm-path gate (MXNET_TUNE, default off): resolve
    knob values from the persistent tuning DB.  Replay only — the warm
    path never searches (mxnet_tpu/tuning)."""
    return get_bool("MXNET_TUNE", False)


def tune_db_dir():
    """Directory for the persistent tuning DB (MXNET_TUNE_DB_DIR;
    unset = no DB — bench.py --tune needs it to persist winners and
    the warm path needs it to replay them)."""
    return get_str("MXNET_TUNE_DB_DIR")


def ledger_skew_threshold():
    """Cross-rank collective-ledger position divergence that arms the
    pre-hang alert (MXNET_LEDGER_SKEW_THRESHOLD, default 0 = off;
    telemetry_agg's merge hook)."""
    return max(0, get_int("MXNET_LEDGER_SKEW_THRESHOLD", 0))


def ledger_skew_windows():
    """Consecutive above-threshold aggregation merges before the
    ledger-skew alert fires (MXNET_LEDGER_SKEW_WINDOWS, default 3)."""
    return max(1, get_int("MXNET_LEDGER_SKEW_WINDOWS", 3))


def goodput_slo():
    """Goodput-ratio SLO threshold in [0, 1] (MXNET_GOODPUT_SLO,
    default 0 = alerting off)."""
    return min(1.0, max(0.0, get_float("MXNET_GOODPUT_SLO", 0.0)))


def goodput_slo_windows():
    """Consecutive below-SLO windows (completed steps) before the
    goodput alert fires (MXNET_GOODPUT_SLO_WINDOWS, default 3)."""
    return max(1, get_int("MXNET_GOODPUT_SLO_WINDOWS", 3))


def trace_requests():
    """Per-request serving trace recording (MXNET_TRACE_REQUESTS,
    default 1; 0 disables span/event capture — the bench A/B knob;
    serving/tracing.py)."""
    return get_bool("MXNET_TRACE_REQUESTS", True)


def trace_keep_slowest():
    """Tail-based retention: how many of the SLOWEST completed request
    traces are always kept alongside the recent ring and the
    error/evicted set (MXNET_TRACE_KEEP_SLOWEST, default 16)."""
    return max(1, get_int("MXNET_TRACE_KEEP_SLOWEST", 16))


def guard_enabled():
    """Numerical-integrity guard master gate (MXNET_GUARD, default 0;
    mxnet_tpu/guard.py — the fused sentinel check + skip/rewind
    remediation ladder)."""
    return get_bool("MXNET_GUARD", False)


def guard_window():
    """Trailing robust-window length for the guard's loss/grad-norm
    spike baselines and the anomaly counter (MXNET_GUARD_WINDOW,
    default 64 steps)."""
    return max(8, get_int("MXNET_GUARD_WINDOW", 64))


def guard_loss_spike():
    """Robust-z threshold above the window median that classifies a
    loss as loss_spike (MXNET_GUARD_LOSS_SPIKE, default 10.0;
    <= 0 disables the loss-spike sentinel)."""
    return get_float("MXNET_GUARD_LOSS_SPIKE", 10.0)


def guard_grad_spike():
    """Robust-z threshold above the window median that classifies a
    global grad-norm as grad_anomaly (MXNET_GUARD_GRAD_SPIKE,
    default 10.0; <= 0 disables the grad-anomaly sentinel)."""
    return get_float("MXNET_GUARD_GRAD_SPIKE", 10.0)


def guard_skip():
    """Skip-step tier of the remediation ladder: zero the update on an
    anomalous verdict (MXNET_GUARD_SKIP, default 1; 0 = verdict-only
    observation mode, updates always commit)."""
    return get_bool("MXNET_GUARD_SKIP", True)


def guard_rewind_after():
    """Anomalous verdicts within the trailing window before the ladder
    escalates from skip to a latest-valid-checkpoint rewind
    (MXNET_GUARD_REWIND_AFTER, default 0 = rewind tier off; needs
    Guard.bind_rewind)."""
    return max(0, get_int("MXNET_GUARD_REWIND_AFTER", 0))


def guard_sync_every():
    """Issue the guard's agreement collective + host sync every N-th
    check (MXNET_GUARD_SYNC_EVERY, default 1 = every guarded step;
    off-cycle checks return the last agreed verdict — anomaly latency
    grows to at most N steps, the MXNET_STOP_SYNC_EVERY shape)."""
    return max(1, get_int("MXNET_GUARD_SYNC_EVERY", 1))


def guard_checksum():
    """Quarantine tier: stamp post-allreduce per-bucket checksums into
    the flight recorder for offline cross-rank SDC blame
    (MXNET_GUARD_CHECKSUM, default 0; independent of MXNET_GUARD so
    evidence collection can be armed without changing step
    semantics)."""
    return get_bool("MXNET_GUARD_CHECKSUM", False)


def guard_canary_every():
    """Deterministic canary-microbatch recompute + cross-rank digest
    vote every N guarded steps (MXNET_GUARD_CANARY_EVERY, default 0 =
    canary off; a minority digest raises NumericalDivergence on every
    rank)."""
    return max(0, get_int("MXNET_GUARD_CANARY_EVERY", 0))


def device_peak_flops_override():
    """Manual per-device peak FLOP/s for online MFU accounting
    (MXNET_DEVICE_PEAK_FLOPS, default 0 = use the TPU device-kind
    table; required on backends the table does not know — without a
    peak the MFU gauge stays absent; mxnet_tpu/introspection.py)."""
    return max(0.0, get_float("MXNET_DEVICE_PEAK_FLOPS", 0.0))


def describe():
    """One line per known var: current value and what it maps to."""
    lines = []
    wired = [
        ("MXNET_ENGINE_TYPE", "determinism switch (engine.set_engine_type)"),
        ("MXNET_NAN_CHECK", "NaN/Inf sanitizer at the dispatch seam "
         "(engine.set_nan_check)"),
        ("MXNET_TPU_MATMUL_PRECISION",
         "fp32 MXU precision (engine.set_matmul_precision)"),
        ("MXNET_SEED", "global RNG seed at import (random.seed)"),
        ("MXNET_CPU_WORKER_NTHREADS", "decode/augment pool width"),
        ("MXNET_PROFILER_AUTOSTART", "start profiler at import"),
        ("MXNET_KVSTORE_BIGARRAY_BOUND", "dist kvstore bucket threshold"),
        ("MXNET_FLASH_BLOCK_Q", "flash-attention q tile (default 128)"),
        ("MXNET_FLASH_BLOCK_KV", "flash-attention kv tile (default 128)"),
        ("MXNET_COORDINATOR_ADDRESS", "jax.distributed coordinator"),
        ("MXNET_TEST_TPU", "real-chip test lane"),
        ("MXNET_EAGER_JIT", "eager jit-cache fast path (default 1; "
         "ndarray/dispatch_cache.py)"),
        ("MXNET_EAGER_JIT_CACHE_SIZE", "dispatch-cache LRU capacity "
         "(default 1024)"),
        ("MXNET_MP_START_METHOD", "DataLoader process-worker start method "
         "(default spawn)"),
        ("MXNET_BENCH_FORCE_SWEEP", "run TPU-gated bench sweeps on CPU"),
        ("MXNET_FAULT_SPEC", "deterministic fault injection spec "
         "(<seam>:fail[:times[:Error]], comma-separated; mxnet_tpu.fault)"),
        ("MXNET_FAULT_MAX_RETRIES", "transient-error retry budget at "
         "hardened seams (default 3)"),
        ("MXNET_FAULT_BACKOFF_MS", "retry/restart backoff seed in ms "
         "(default 100; doubles per retry, full jitter)"),
        ("MXNET_TELEMETRY_PORT", "opt-in HTTP telemetry endpoint "
         "(/metrics Prometheus, /snapshot JSON; unset/0 = off)"),
        ("MXNET_TELEMETRY_TIMELINE_STEPS", "step-timeline ring capacity "
         "(default 256; mxnet_tpu.telemetry)"),
        ("MXNET_TELEMETRY_COMPILE_EVENTS", "compile-event ring capacity "
         "(default 512; mxnet_tpu.telemetry)"),
        ("MXNET_TELEMETRY_AGG_EVERY", "cross-rank snapshot aggregation "
         "stride in step-boundary ticks (default 0 = off; "
         "mxnet_tpu/telemetry_agg.py)"),
        ("MXNET_TELEMETRY_AGG_DIR", "shared directory for per-rank "
         "snapshot files the aggregator merges (unset = aggregation "
         "off)"),
        ("MXNET_TELEMETRY_AGG_TRANSPORT", "cross-rank snapshot gather "
         "transport: file (shared dir, default) or kv (jax.distributed "
         "KV store; black-box dumps stay file-based)"),
        ("MXNET_FLIGHT_RECORDER", "distributed flight recorder: "
         "per-rank collective ledger ring (default 1; "
         "mxnet_tpu/flight_recorder.py)"),
        ("MXNET_FLIGHT_RECORDER_CAP", "flight-recorder ring capacity "
         "in events (default 4096)"),
        ("MXNET_FLIGHT_DIR", "directory for blackbox.rank<N>.json "
         "crash dumps (default = MXNET_TELEMETRY_AGG_DIR; neither set "
         "= dumps skipped)"),
        ("MXNET_TUNE", "autotuning warm path: replay stored winners "
         "from the tuning DB (default 0; env pins always win; "
         "mxnet_tpu/tuning)"),
        ("MXNET_TUNE_DB_DIR", "directory for the persistent tuning DB "
         "(bench.py --tune writes, MXNET_TUNE=1 replays; unset = no "
         "DB)"),
        ("MXNET_LEDGER_SKEW_THRESHOLD", "cross-rank ledger-position "
         "divergence arming the pre-hang alert (default 0 = off; "
         "sustained N merges fires once per episode)"),
        ("MXNET_LEDGER_SKEW_WINDOWS", "consecutive above-threshold "
         "aggregation merges before the ledger-skew alert fires "
         "(default 3)"),
        ("MXNET_GOODPUT_SLO", "goodput-ratio SLO threshold (default 0 "
         "= alerting off; below it for N windows fires the breach "
         "alert)"),
        ("MXNET_GOODPUT_SLO_WINDOWS", "consecutive below-SLO windows "
         "(completed steps) before the goodput alert fires "
         "(default 3)"),
        ("MXNET_TRACE_REQUESTS", "per-request serving span traces "
         "(default 1; 0 = no capture; serving/tracing.py)"),
        ("MXNET_TRACE_KEEP_SLOWEST", "slowest-N request traces always "
         "retained (tail-based retention; default 16)"),
        ("MXNET_DEVICE_PEAK_FLOPS", "per-device peak FLOP/s override "
         "for online MFU (default 0 = TPU device-kind table; "
         "mxnet_tpu/introspection.py)"),
        ("MXNET_GUARD", "numerical-integrity guard: fused sentinel "
         "check + skip/rewind ladder (default 0; mxnet_tpu/guard.py)"),
        ("MXNET_GUARD_WINDOW", "trailing robust-window length for the "
         "guard's spike baselines and anomaly counter (default 64)"),
        ("MXNET_GUARD_LOSS_SPIKE", "robust-z loss-spike threshold over "
         "the window median (default 10.0; <= 0 = sentinel off)"),
        ("MXNET_GUARD_GRAD_SPIKE", "robust-z grad-norm anomaly "
         "threshold over the window median (default 10.0; <= 0 = "
         "sentinel off)"),
        ("MXNET_GUARD_SKIP", "skip-step tier: zero the update on an "
         "anomalous verdict (default 1; 0 = observe only)"),
        ("MXNET_GUARD_REWIND_AFTER", "anomalies in the window before "
         "skip escalates to a latest-valid-checkpoint rewind "
         "(default 0 = rewind tier off)"),
        ("MXNET_GUARD_SYNC_EVERY", "guard agreement collective + host "
         "sync every N-th check (default 1; off-cycle returns the "
         "last agreed verdict)"),
        ("MXNET_GUARD_CHECKSUM", "quarantine tier: post-allreduce "
         "per-bucket checksum stamps for offline SDC blame "
         "(default 0)"),
        ("MXNET_GUARD_CANARY_EVERY", "deterministic canary recompute + "
         "cross-rank digest vote every N guarded steps (default 0 = "
         "off; minority digest raises NumericalDivergence)"),
        ("MXNET_PREFETCH_BUFFER", "device-prefetch queue depth "
         "(default 2; 0 = no background pipeline; "
         "gluon/data/prefetcher.py)"),
        ("MXNET_ALLREDUCE_BUCKET_MB", "fused-allreduce bucket cap in MiB "
         "(default 32; 0 = per-key collectives; parallel/bucketing.py)"),
        ("MXNET_ZERO", "ZeRO-1 optimizer-state sharding on the bucketed "
         "grad path (default 0 = replicated; parallel/zero.py)"),
        ("MXNET_CHECKPOINT_ASYNC", "default for CheckpointManager.save "
         "async_ (unset/0 = synchronous saves)"),
        ("MXNET_WATCHDOG_TIMEOUT_S", "per-step stall deadline in seconds "
         "(default 0 = watchdog off; mxnet_tpu.lifecycle)"),
        ("MXNET_WATCHDOG_ABORT", "tripped watchdog exits the process after "
         "the diagnosis dump (default 1; 0 = diagnose only)"),
        ("MXNET_WATCHDOG_DIR", "directory for watchdog stall-diagnosis "
         "files (default cwd)"),
        ("MXNET_GRACE_PERIOD_S", "preemption-signal → forced-exit deadline "
         "(default 0 = none; match the scheduler's SIGTERM grace)"),
        ("MXNET_PREEMPTION_CHECKPOINT", "final synchronous checkpoint on a "
         "graceful preemption stop (default 1)"),
        ("MXNET_LIFECYCLE_SIGNALS", "distributed.init installs graceful "
         "SIGTERM/SIGINT handlers (default 1)"),
        ("MXNET_STOP_SYNC_EVERY", "stop-agreement collective every N-th "
         "check_stop (default 1; N steps max stop latency)"),
        ("MXNET_SERVING_PORT", "serving.serve HTTP endpoint port "
         "(default 0 = pick free; routes mount beside /metrics)"),
        ("MXNET_SERVING_MAX_BATCH", "serving decode-batch admission cap "
         "(default 8)"),
        ("MXNET_SERVING_BATCH_BUCKETS", "decode batch-size buckets the "
         "engine AOT-compiles (default 1,2,4,8)"),
        ("MXNET_SERVING_PREFILL_BUCKETS", "prompt-length prefill buckets "
         "(default 32,64,128)"),
        ("MXNET_SERVING_QUEUE", "serving admission-queue bound "
         "(default 64; full = clean 429 rejection)"),
        ("MXNET_SERVING_KV_PAGES", "KV-cache pool pages (default 512; "
         "page 0 reserved as scratch; serving/kvcache.py)"),
        ("MXNET_SERVING_PAGE_SIZE", "tokens per KV-cache page "
         "(default 16)"),
        ("MXNET_SERVING_DEADLINE_MS", "default per-request serving "
         "deadline in ms (default 0 = none)"),
        ("MXNET_FLEET_REPLICAS", "serving-fleet replica count behind "
         "the router (default 2; serving/fleet)"),
        ("MXNET_FLEET_HEDGE_MS", "hedged-duplicate delay floor in ms "
         "(default 50; effective delay = max(floor, observed p99))"),
        ("MXNET_FLEET_RETRY_BUDGET", "per-request transient-retry "
         "budget for router→replica dispatch (default 2)"),
        ("MXNET_FLEET_PROBE_INTERVAL_MS", "router health-probe period "
         "in ms (default 250; dead-replica detection < 1s)"),
        ("MXNET_FLEET_EJECT_THRESHOLD", "consecutive failures before "
         "the circuit breaker ejects a replica (default 3)"),
        ("MXNET_PLANNER_MESH", "default planner mesh: auto or "
         "\"dp=4,tp=2\"-style spec (parallel/planner)"),
        ("MXNET_PLANNER_HBM_GB", "per-device HBM budget in GiB for "
         "planner auto mesh selection (default 16.0)"),
        ("MXNET_PLANNER_PIPELINE_IN_JIT", "P(pp) in_specs for traced "
         "pipeline stage params instead of the GSPMD replicated "
         "workaround (default 0; flip after a jax upgrade)"),
        ("MXNET_PLANNER_REPORT", "print the visualize_sharding report "
         "at plan time (default 0)"),
        ("MXNET_GRAPH_PIPELINE", "graph-compiler pass pipeline on the "
         "hybridize/TrainStep/serving trace seam (default 1; "
         "mxnet_tpu/graph)"),
        ("MXNET_GRAPH_PASSES", "graph-pass selection (csv; \"-name\" "
         "subtracts from the default catalog; unset = defaults)"),
        ("MXNET_GRAPH_FUSE_CAP", "max ops per fused elementwise chain "
         "(default 16; < 2 disables fusion)"),
        ("MXNET_RESHARD_INFLIGHT_MB", "in-flight byte budget per live "
         "resharding transfer round (default 64 MiB; "
         "parallel/resharding.py)"),
        ("MXNET_COMPILE_CACHE", "persistent warm-start compile cache "
         "gate (default 1; needs a directory — see "
         "MXNET_COMPILE_CACHE_DIR; mxnet_tpu/compile_cache.py)"),
        ("MXNET_COMPILE_CACHE_DIR", "directory for the session-default "
         "compile cache (unset = only checkpoint-side caches)"),
        ("MXNET_COMPILE_CACHE_SALT", "manual cache-invalidation key "
         "component (bump when Python semantics change under an "
         "unchanged signature)"),
        ("MXNET_SUBGRAPH_BACKEND", "subgraph backend applied at Module "
         "bind time (mxnet_tpu.subgraph; unset = none)"),
        ("MXNET_NUM_WORKERS", "launcher world size for distributed.init "
         "(alias DMLC_NUM_WORKER; default 1)"),
        ("MXNET_WORKER_ID", "launcher rank for distributed.init + "
         "checkpoint primary election (alias DMLC_WORKER_ID)"),
    ]
    for name, what in wired:
        lines.append(f"{name}={os.environ.get(name, '<unset>')} — {what}")
    for name, why in _SUBSUMED.items():
        lines.append(f"{name}={os.environ.get(name, '<unset>')} — subsumed: "
                     f"{why}")
    return "\n".join(lines)


def apply_env():
    """Apply import-time vars (called once from mxnet_tpu/__init__)."""
    seed = os.environ.get("MXNET_SEED")
    if seed:
        from . import random as _random

        _random.seed(int(seed))
    if get_bool("MXNET_PROFILER_AUTOSTART"):
        from . import profiler

        profiler.set_config(profile_all=True)
        profiler.start()
    if watchdog_timeout_s() > 0:
        from . import lifecycle

        lifecycle.start_watchdog()
    port = get_int("MXNET_TELEMETRY_PORT", 0)
    if port > 0:
        from . import telemetry

        try:
            telemetry.start_http_server(port)
        except OSError as e:
            # spawned DataLoader workers and same-host multi-rank peers
            # inherit the env var but cannot bind the parent's port —
            # telemetry recording still works, only the endpoint is theirs
            # to miss; crashing the import would kill the worker pool
            import warnings

            warnings.warn(
                f"MXNET_TELEMETRY_PORT={port}: endpoint not started "
                f"({e}); another process on this host (parent/rank 0?) "
                "likely holds the port", stacklevel=2)
