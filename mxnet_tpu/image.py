"""Image utilities (reference: python/mxnet/image/image.py — imread,
imresize, fixed/random crop, color normalize, ImageIter)."""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["imread", "imresize", "resize_short", "fixed_crop", "center_crop",
           "random_crop", "color_normalize", "ImageIter"]


def imread(filename, flag=1, to_rgb=True):
    if filename.endswith(".npy"):
        return array(_np.load(filename))
    try:
        from PIL import Image
    except ImportError as e:
        raise MXNetError("imread of encoded images needs PIL; .npy works "
                         "without it") from e
    img = _np.asarray(Image.open(filename))
    if flag == 0 and img.ndim == 3:
        img = img.mean(axis=-1, keepdims=True).astype(img.dtype)
    return array(img)


def imresize(src, w, h, interp=1):
    import jax
    import jax.numpy as jnp

    v = src._get() if isinstance(src, NDArray) else jnp.asarray(_np.asarray(src))
    out = jax.image.resize(v.astype(jnp.float32), (h, w, v.shape[2]),
                           method="bilinear" if interp else "nearest")
    return NDArray._from_jax(out.astype(v.dtype), getattr(src, "context", None))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _np.random.randint(0, w - new_w + 1)
    y0 = _np.random.randint(0, h - new_h + 1)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src if isinstance(src, NDArray) else array(src)
    out = src - (mean if isinstance(mean, NDArray) else array(_np.asarray(mean)))
    if std is not None:
        out = out / (std if isinstance(std, NDArray) else array(_np.asarray(std)))
    return out


class ImageIter:
    """Python-side image iterator over .rec or image list (reference:
    mx.image.ImageIter).  Minimal: rec-file batching with resize/crop."""

    def __init__(self, batch_size, data_shape, path_imgrec=None, shuffle=False,
                 aug_list=None, **kwargs):
        from .recordio import MXIndexedRecordIO, unpack_img

        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec here")
        idx = path_imgrec[:path_imgrec.rfind(".")] + ".idx"
        self._rec = MXIndexedRecordIO(idx, path_imgrec, "r")
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.shuffle = shuffle
        self._unpack_img = unpack_img
        self._order = list(self._rec.keys)
        self._pos = 0
        self.reset()

    def reset(self):
        self._pos = 0
        if self.shuffle:
            _np.random.shuffle(self._order)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .io import DataBatch

        if self._pos + self.batch_size > len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), dtype=_np.float32)
        label = _np.zeros((self.batch_size,), dtype=_np.float32)
        for i in range(self.batch_size):
            rec = self._rec.read_idx(self._order[self._pos + i])
            hdr, img = self._unpack_img(rec)
            img = _np.asarray(imresize(array(img), w, h).asnumpy())
            if img.ndim == 2:
                img = img[:, :, None]
            data[i] = img.transpose(2, 0, 1)[:c]
            label[i] = hdr.label if _np.isscalar(hdr.label) else hdr.label[0]
        self._pos += self.batch_size
        return DataBatch(data=[array(data)], label=[array(label)])
