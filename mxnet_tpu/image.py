"""Image utilities (reference: python/mxnet/image/image.py — imread,
imresize, crops, color ops, the Augmenter architecture, CreateAugmenter,
ImageIter; python/mxnet/image/detection.py — DetAugmenter family,
CreateDetAugmenter, ImageDetIter).

Augmentation runs host-side in numpy (the same place the reference's
augmenters run: on the decode worker, before batching/device transfer);
the TPU sees only the batched tensor."""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["imread", "imresize", "resize_short", "fixed_crop", "center_crop",
           "random_crop", "random_size_crop", "color_normalize", "ImageIter",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "CastAug", "RandomCropAug", "CenterCropAug",
           "RandomSizedCropAug", "HorizontalFlipAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "CreateAugmenter",
           "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


def imread(filename, flag=1, to_rgb=True):
    if filename.endswith(".npy"):
        return array(_np.load(filename))
    try:
        from PIL import Image
    except ImportError as e:
        raise MXNetError("imread of encoded images needs PIL; .npy works "
                         "without it") from e
    img = _np.asarray(Image.open(filename))
    if flag == 0 and img.ndim == 3:
        img = img.mean(axis=-1, keepdims=True).astype(img.dtype)
    return array(img)


def imresize(src, w, h, interp=1):
    import jax
    import jax.numpy as jnp

    v = src._get() if isinstance(src, NDArray) else jnp.asarray(_np.asarray(src))
    out = jax.image.resize(v.astype(jnp.float32), (h, w, v.shape[2]),
                           method="bilinear" if interp else "nearest")
    return NDArray._from_jax(out.astype(v.dtype), getattr(src, "context", None))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _np.random.randint(0, w - new_w + 1)
    y0 = _np.random.randint(0, h - new_h + 1)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src if isinstance(src, NDArray) else array(src)
    out = src - (mean if isinstance(mean, NDArray) else array(_np.asarray(mean)))
    if std is not None:
        out = out / (std if isinstance(std, NDArray) else array(_np.asarray(std)))
    return out


class ImageIter:
    """Python-side image iterator over .rec or image list (reference:
    mx.image.ImageIter).  ``aug_list`` (e.g. from :func:`CreateAugmenter`)
    runs per decoded image; without one, images are resized to
    ``data_shape``."""

    def __init__(self, batch_size, data_shape, path_imgrec=None, shuffle=False,
                 aug_list=None, **kwargs):
        from .recordio import MXIndexedRecordIO, unpack_img

        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec here")
        idx = path_imgrec[:path_imgrec.rfind(".")] + ".idx"
        self._rec = MXIndexedRecordIO(idx, path_imgrec, "r")
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.shuffle = shuffle
        self.auglist = aug_list
        self._unpack_img = unpack_img
        self._order = list(self._rec.keys)
        self._pos = 0
        self.reset()

    def reset(self):
        self._pos = 0
        if self.shuffle:
            _np.random.shuffle(self._order)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .io import DataBatch

        if self._pos + self.batch_size > len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), dtype=_np.float32)
        label = _np.zeros((self.batch_size,), dtype=_np.float32)
        for i in range(self.batch_size):
            rec = self._rec.read_idx(self._order[self._pos + i])
            hdr, img = self._unpack_img(rec)
            if self.auglist:
                img_nd = array(_np.asarray(img))
                for aug in self.auglist:
                    img_nd = aug(img_nd)
                img = _as_np(img_nd)
            else:
                img = _np.asarray(imresize(array(img), w, h).asnumpy())
            if img.ndim == 2:
                img = img[:, :, None]
            data[i] = img.transpose(2, 0, 1)[:c]
            label[i] = hdr.label if _np.isscalar(hdr.label) else hdr.label[0]
        self._pos += self.batch_size
        return DataBatch(data=[array(data)], label=[array(label)])


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop with area/aspect constraints (reference:
    image.random_size_crop — the inception-style crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _np.random.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_np.random.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * aspect)))
        new_h = int(round(_np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _np.random.randint(0, w - new_w + 1)
            y0 = _np.random.randint(0, h - new_h + 1)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def _as_np(src):
    return src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)


# ==========================================================================
# Augmenter architecture (reference: image.py Augmenter and subclasses)
# ==========================================================================
class Augmenter:
    """Image augmentation base (reference: mx.image.Augmenter).  Call with
    an HWC image NDArray, get the augmented NDArray back."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, _np.ndarray):
                kwargs[k] = v.tolist()

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src

    def dumps(self):
        return [self.__class__.__name__, [t.dumps() for t in self.ts]]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        order = _np.random.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src

    def dumps(self):
        return [self.__class__.__name__, [t.dumps() for t in self.ts]]


class ResizeAug(Augmenter):
    """Resize shorter edge to size (reference: ResizeAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """Resize to exact (w, h) ignoring aspect (reference: ForceResizeAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return array(_as_np(src).astype(self.typ))


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            return array(_as_np(src)[:, ::-1].copy())
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.brightness, self.brightness)
        return array(_as_np(src) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "f")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.contrast, self.contrast)
        s = _as_np(src).astype("f")
        gray = (s * self._coef).sum() * (3.0 / s.size)
        return array(s * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "f")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.saturation, self.saturation)
        s = _as_np(src).astype("f")
        gray = (s * self._coef).sum(axis=2, keepdims=True)
        return array(s * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference: HueJitterAug's tyiq route)."""
    _tyiq = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], "f")
    _ityiq = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], "f")

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = _np.random.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]], "f")
        t = self._ityiq @ bt @ self._tyiq
        s = _as_np(src).astype("f")
        return array(s @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-noise lighting (reference: LightingAug, AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, "f")
        self.eigvec = _np.asarray(eigvec, "f")

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha) @ self.eigval
        return array(_as_np(src).astype("f") + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = None if mean is None else _np.asarray(mean, "f")
        self.std = None if std is None else _np.asarray(std, "f")

    def __call__(self, src):
        s = _as_np(src).astype("f")
        if self.mean is not None:
            s = s - self.mean
        if self.std is not None:
            s = s / self.std
        return array(s)


class RandomGrayAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            s = _as_np(src).astype("f")
            gray = (s @ _np.array([0.299, 0.587, 0.114], "f"))[..., None]
            return array(_np.repeat(gray, 3, axis=2))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Standard augmenter pipeline factory (reference:
    mx.image.CreateAugmenter — same knob set, same order)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ==========================================================================
# Detection augmenters (reference: python/mxnet/image/detection.py)
# Label format: (N, 5+) float rows [cls_id, xmin, ymin, xmax, ymax, ...] with
# coordinates normalized to [0, 1] (the reference's internal format after
# its header parse).
# ==========================================================================
class DetAugmenter:
    """Detection augmentation base: __call__(src, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image Augmenter for detection (reference: DetBorrowAug
    — geometry-preserving augmenters only)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one of several augmenters (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _np.random.rand() < self.skip_prob or not self.aug_list:
            return src, label
        i = _np.random.randint(len(self.aug_list))
        return self.aug_list[i](src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.rand() < self.p:
            src = array(_as_np(src)[:, ::-1].copy())
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


def _bbox_overlap(boxes, crop):
    """Fraction of each box's area covered by crop (both normalized)."""
    x1 = _np.maximum(boxes[:, 0], crop[0])
    y1 = _np.maximum(boxes[:, 1], crop[1])
    x2 = _np.minimum(boxes[:, 2], crop[2])
    y2 = _np.minimum(boxes[:, 3], crop[3])
    inter = _np.maximum(x2 - x1, 0) * _np.maximum(y2 - y1, 0)
    area = _np.maximum((boxes[:, 2] - boxes[:, 0])
                       * (boxes[:, 3] - boxes[:, 1]), 1e-12)
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """Random crop with object-coverage constraints (reference:
    DetRandomCropAug — SSD-style constrained sampling)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        valid = label[label[:, 0] >= 0]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ar = _np.random.uniform(*self.aspect_ratio_range)
            cw = min(_np.sqrt(area * ar), 1.0)
            ch = min(_np.sqrt(area / ar), 1.0)
            cx = _np.random.uniform(0, 1.0 - cw)
            cy = _np.random.uniform(0, 1.0 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if len(valid) == 0:
                return crop
            cov = _bbox_overlap(valid[:, 1:5], crop)
            if cov.max() >= self.min_object_covered:
                return crop
        return None

    def __call__(self, src, label):
        crop = self._sample_crop(label)
        if crop is None:
            return src, label
        h, w = src.shape[:2]
        x0, y0 = int(crop[0] * w), int(crop[1] * h)
        cw, ch = max(int((crop[2] - crop[0]) * w), 1), \
            max(int((crop[3] - crop[1]) * h), 1)
        out = fixed_crop(src, x0, y0, cw, ch)
        new_label = []
        for row in label:
            if row[0] < 0:
                continue
            cov = _bbox_overlap(row[None, 1:5], crop)[0]
            if cov < self.min_eject_coverage:
                continue
            b = row.copy()
            b[1] = (max(row[1], crop[0]) - crop[0]) / (crop[2] - crop[0])
            b[2] = (max(row[2], crop[1]) - crop[1]) / (crop[3] - crop[1])
            b[3] = (min(row[3], crop[2]) - crop[0]) / (crop[2] - crop[0])
            b[4] = (min(row[4], crop[3]) - crop[1]) / (crop[3] - crop[1])
            new_label.append(b)
        if not new_label:
            return src, label  # keep original rather than emit empty
        out_label = _np.full_like(label, -1.0)
        out_label[:len(new_label)] = _np.stack(new_label)
        return out, out_label


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad (reference: DetRandomPadAug — zoom-out)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        s = _as_np(src)
        h, w = s.shape[:2]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ar = _np.random.uniform(*self.aspect_ratio_range)
            nw, nh = int(w * _np.sqrt(area * ar)), int(h * _np.sqrt(area / ar))
            if nw >= w and nh >= h:
                x0 = _np.random.randint(0, nw - w + 1)
                y0 = _np.random.randint(0, nh - h + 1)
                canvas = _np.empty((nh, nw) + s.shape[2:], dtype=s.dtype)
                canvas[...] = _np.asarray(self.pad_val, dtype=s.dtype)
                canvas[y0:y0 + h, x0:x0 + w] = s
                label = label.copy()
                valid = label[:, 0] >= 0
                label[valid, 1] = (label[valid, 1] * w + x0) / nw
                label[valid, 2] = (label[valid, 2] * h + y0) / nh
                label[valid, 3] = (label[valid, 3] * w + x0) / nw
                label[valid, 4] = (label[valid, 4] * h + y0) / nh
                return array(canvas), label
        return src, label


class _DetForceResizeAug(DetAugmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1], self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Detection pipeline factory (reference: mx.image.CreateDetAugmenter —
    same knobs; crop/pad probabilities select constrained samplers)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to network input size
    auglist.append(_DetForceResizeAug((data_shape[2], data_shape[1]),
                                      inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator over a .rec file (reference: mx.image.ImageDetIter).

    Record labels use the reference's detection header:
    ``[header_width, object_width, (extras...), obj0..., obj1...]`` where
    each object is ``[cls_id, xmin, ymin, xmax, ymax, ...]`` normalized.
    Batch label shape is (batch, max_objects, object_width), padded with -1
    rows.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None, shuffle=False,
                 aug_list=None, **kwargs):
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         shuffle=shuffle, **kwargs)
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        self.auglist = aug_list
        from .recordio import unpack as _unpack_header

        # first pass over headers to size the padded label tensor — headers
        # only (recordio.unpack leaves the image payload undecoded)
        self._obj_width, self._max_objs = 5, 1
        for k in self._rec.keys:
            hdr, _ = _unpack_header(self._rec.read_idx(k))
            objs = self._split_objects(_np.asarray(hdr.label, "f").ravel())
            self._obj_width = max(self._obj_width, objs.shape[1])
            self._max_objs = max(self._max_objs, len(objs))

    @staticmethod
    def _split_objects(lab):
        """Split a raw label vector into object rows.  Detection headers are
        ``[header_width>=2, object_width>=5, extras..., objs...]`` with
        integral leading fields (reference im2rec layout); anything else is
        plain ``[cls x1 y1 x2 y2]`` rows."""
        if (lab.size >= 2 and lab[0] >= 2 and lab[1] >= 5
                and float(lab[0]).is_integer() and float(lab[1]).is_integer()
                and (lab.size - int(lab[0])) % int(lab[1]) == 0):
            hw, ow = int(lab[0]), int(lab[1])
            return lab[hw:].reshape(-1, ow)
        return lab.reshape(-1, 5)

    def _parse_label(self, hdr):
        objs = self._split_objects(_np.asarray(hdr.label, "f").ravel())
        out = _np.full((self._max_objs, self._obj_width), -1.0, "f")
        out[:len(objs), :objs.shape[1]] = objs
        return out

    def next(self):
        from .io import DataBatch

        if self._pos + self.batch_size > len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), dtype=_np.float32)
        labels = _np.full((self.batch_size, self._max_objs, self._obj_width),
                          -1.0, dtype=_np.float32)
        for i in range(self.batch_size):
            rec = self._rec.read_idx(self._order[self._pos + i])
            hdr, img = self._unpack_img(rec)
            img_nd = array(_np.asarray(img))
            label = self._parse_label(hdr)
            for aug in self.auglist:
                img_nd, label = aug(img_nd, label)
            s = _as_np(img_nd)
            if s.ndim == 2:
                s = s[:, :, None]
            if s.shape[:2] != (h, w):
                # aug list without a sizing step (boxes are normalized, so
                # a plain resize keeps the labels valid)
                s = _as_np(imresize(array(s.astype("float32")), w, h))
            data[i] = s.transpose(2, 0, 1)[:c]
            labels[i] = label
        self._pos += self.batch_size
        return DataBatch(data=[array(data)], label=[array(labels)])
