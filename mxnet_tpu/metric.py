"""Evaluation metrics (reference: python/mxnet/metric.py, ~1.5k LoC:
Accuracy, TopK, F1, MCC, Perplexity, MAE/MSE/RMSE, CrossEntropy, NLL,
PearsonCorr, Custom, Composite — SURVEY.md §3.5)."""
from __future__ import annotations

import math

import numpy as _np

from .base import Registry, MXNetError

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE",
           "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "CustomMetric",
           "CompositeEvalMetric", "create", "np"]

_REG = Registry("metric")


def register(cls):
    _REG.register(cls)
    return cls


# reference short names (python/mxnet/metric.py registers these aliases)
_ALIASES = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
            "negativeloglikelihood", "top_k_accuracy": "topkaccuracy",
            "top_k_acc": "topkaccuracy", "pearsonr": "pearsoncorrelation"}


def create(metric, *args, **kwargs):
    if isinstance(metric, str):
        metric = _ALIASES.get(metric.lower(), metric)
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)


def _to_np(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _check_label_shapes(labels, preds):
    if len(labels) != len(preds):
        raise MXNetError(f"label/pred count mismatch: {len(labels)} vs {len(preds)}")


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        _check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype("int32").ravel()
            pred = _to_np(pred)
            arg = _np.argsort(-pred, axis=1)[:, :self.top_k]
            self.sum_metric += (arg == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        if hasattr(self, "tp"):
            self.reset_stats()

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred)
            pred = (pred[:, 1] > 0.5).astype("int32") if pred.ndim == 2 else (pred.ravel() > 0.5).astype("int32")
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            prec = self.tp / max(self.tp + self.fp, 1)
            rec = self.tp / max(self.tp + self.fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.tp = self.fp = self.fn = self.tn = 0

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = self.tn = 0

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred)
            pred = (pred[:, 1] > 0.5).astype("int32") if pred.ndim == 2 else (pred.ravel() > 0.5).astype("int32")
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn) *
                              (self.tn + self.fp) * (self.tn + self.fn))
            mcc = ((self.tp * self.tn - self.fp * self.fn) / denom) if denom else 0.0
            self.sum_metric = mcc
            self.num_inst = 1


def _align_label(label, pred):
    """Reference behavior: 1-d labels broadcast against (n, k) preds."""
    if label.shape == pred.shape:
        return label
    if label.ndim == 1:
        label = label.reshape(label.shape[0], 1)
    if label.size == pred.size:
        return label.reshape(pred.shape)
    return label  # rely on numpy broadcasting


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += _np.abs(_align_label(label, pred) - pred).mean() * len(label)
            self.num_inst += len(label)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += ((_align_label(label, pred) - pred) ** 2).mean() * len(label)
            self.num_inst += len(label)


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += math.sqrt(((_align_label(label, pred) - pred) ** 2).mean()) * len(label)
            self.num_inst += len(label)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += len(label)


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype("int32")
            pred = _to_np(pred).reshape(-1, _to_np(pred).shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = _np.where(ignore, 1.0, prob)
                num -= int(ignore.sum())
            loss += -_np.log(_np.maximum(prob, 1e-10)).sum()
            num += len(label)
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label).ravel(), _to_np(pred).ravel()
            r = _np.corrcoef(label, pred)[0, 1]
            self.sum_metric += r
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_to_np(label), _to_np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", name)
    return CustomMetric(feval, name=feval.__name__,
                        allow_extra_outputs=allow_extra_outputs)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
