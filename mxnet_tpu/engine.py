"""Execution-engine controls: numeric precision policy + determinism switch.

Reference: the engine in MXNet 1.x is configured through env vars read by
``src/engine/engine.cc`` — ``MXNET_ENGINE_TYPE=NaiveEngine`` turns the async
threaded engine into a synchronous, deterministic one (SURVEY.md §5 oracle 5,
§6.6 env-var layer).  The TPU build's "engine" is the JAX/XLA runtime, so the
two knobs map to:

- **Matmul precision** (``MXNET_TPU_MATMUL_PRECISION``): on TPU the MXU
  multiplies fp32 operands via bf16 passes at XLA's *default* precision,
  which silently degrades fp32 semantics (observed: flash-attention rows
  attending few keys drift 8%+ relative, CPU-vs-TPU Convolution diverges
  past a 2e-2 ladder).  The TPU-native stance: **fp32 means fp32** — speed
  comes from *explicitly* choosing bf16 (AMP / ``dtype='bfloat16'``), not
  from silently truncating fp32.  Default is therefore ``highest``
  (bf16x6/fp32-accurate passes); bf16 inputs are unaffected (single MXU
  pass is already exact for them), so the benchmark path loses nothing.
- **Determinism/naive engine** (``MXNET_ENGINE_TYPE=NaiveEngine`` or
  :func:`set_engine_type`): maps to ``jax.disable_jit`` — ops execute
  eagerly, op-by-op, in deterministic program order with no fusion, the
  direct analog of NaiveEngine's synchronous single-op execution.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["set_matmul_precision", "set_engine_type", "engine_type",
           "naive_engine", "set_nan_check", "nan_check_enabled"]

_VALID_PRECISION = ("default", "high", "highest", "bfloat16",
                    "tensorfloat32", "float32")
_engine_type = "ThreadedEnginePerDevice"  # reference default engine name


def set_matmul_precision(precision):
    """Set XLA's default matmul/conv precision for fp32 operands.

    ``highest`` (default) = fp32-accurate MXU passes; ``default`` = XLA's
    native bf16-pass behavior (fastest fp32, loosest numerics).
    """
    import jax

    if precision not in _VALID_PRECISION:
        from .base import MXNetError

        raise MXNetError(
            f"unknown matmul precision {precision!r}; one of {_VALID_PRECISION}")
    if precision == "default":
        jax.config.update("jax_default_matmul_precision", None)
    else:
        jax.config.update("jax_default_matmul_precision", precision)


def _init_from_env():
    prec = os.environ.get("MXNET_TPU_MATMUL_PRECISION", "highest")
    if prec != "default":
        try:
            set_matmul_precision(prec)
        except Exception:
            # an env-var typo must not make `import mxnet_tpu` raise
            import warnings

            warnings.warn(
                f"MXNET_TPU_MATMUL_PRECISION={prec!r} not recognized; "
                "falling back to 'highest'", stacklevel=2)
            set_matmul_precision("highest")
    if os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine":
        set_engine_type("NaiveEngine")
    if os.environ.get("MXNET_NAN_CHECK", "") in ("1", "true", "True"):
        set_nan_check(True)


def engine_type():
    return _engine_type


def set_engine_type(name):
    """Switch between the async fused engine and the deterministic naive one.

    ``NaiveEngine`` disables jit globally (eager, op-by-op, deterministic
    order — the debugging mode of reference `src/engine/naive_engine.cc`);
    any other reference engine name restores normal jit execution.
    """
    global _engine_type
    import jax

    # jax.disable_jit() the context manager is thread-local; the engine
    # switch must apply process-wide (data-loader/prefetch threads included),
    # so flip the global config value instead.
    jax.config.update("jax_disable_jit", name == "NaiveEngine")
    # the eager jit-cache must not serve fused executables in op-by-op
    # deterministic mode
    from .ndarray import dispatch_cache as _dc

    _dc.set_engine_bypass(name == "NaiveEngine")
    _engine_type = name


@contextlib.contextmanager
def naive_engine():
    """Scoped determinism switch: ``with mx.engine.naive_engine(): ...``"""
    prev = _engine_type
    if prev == "NaiveEngine":
        yield
        return
    set_engine_type("NaiveEngine")
    try:
        yield
    finally:
        set_engine_type(prev)


def set_nan_check(enabled=True):
    """Device-side NaN/Inf sanitizer on the imperative dispatch seam
    (SURVEY.md §6.2: the TPU analog of the reference's sanitizer CI lane;
    env: MXNET_NAN_CHECK=1).  Synchronizes per op while on — a debug mode,
    like NaiveEngine."""
    from .ndarray.ndarray import _NAN_CHECK

    _NAN_CHECK["on"] = bool(enabled)


def nan_check_enabled():
    from .ndarray.ndarray import _NAN_CHECK

    return _NAN_CHECK["on"]
