"""Automatic mixed precision (reference: python/mxnet/contrib/amp/amp.py).

The reference's ``amp.init()`` monkey-patches the generated op namespaces to
insert ``amp_cast``/``amp_multicast`` around allow/deny-listed ops.  Here all
imperative and traced execution funnels through ``ndarray.invoke`` (the
MXImperativeInvokeEx analog), so one hook there applies the cast policy to
every path — eager NDArray code, ``hybridize()`` traces, and the fused
``parallel.TrainStep`` jit (which traces through the same invoke).

Casts are wrapped *inside* the op function so they are part of the traced
computation: under ``jax.vjp`` the cast's transpose casts gradients back to
the master-weight dtype (fp32), which is exactly the mixed-precision
master-weights contract.  XLA fuses the casts into the convolution/matmul
epilogues, so the policy costs no extra HBM passes.
"""
from __future__ import annotations

from contextlib import contextmanager

from ...base import MXNetError
from .loss_scaler import LossScaler
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_model",
           "convert_hybrid_block", "list_fp16_ops", "list_fp32_ops"]

_DEFAULT_TARGET = "bfloat16"

# monotonic policy-install token (never rewinds): two different scoped
# policies can never share a dispatch-cache key even after _cast_scope
# restores earlier state
_EPOCH = iter(range(1, 1 << 62)).__next__


def _amp_dict():
    from ...ndarray.ndarray import _AMP

    return _AMP


def _floating(v):
    import jax.numpy as jnp

    return hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)


def _make_wrap(target_dtype, target_ops, fp32_ops):
    import jax.numpy as jnp

    tgt = jnp.dtype(target_dtype)
    f32 = jnp.dtype("float32")

    def wrap(od, fn):
        name = od.name
        if name in target_ops:
            to = tgt
        elif name in fp32_ops:
            to = f32
        else:
            return fn

        def cast_fn(*arrays):
            cast = tuple(
                a.astype(to) if _floating(a) and a.dtype != to else a
                for a in arrays)
            return fn(*cast)

        return cast_fn

    return wrap


def init(target_dtype=_DEFAULT_TARGET, target_dtype_ops=None, fp32_ops=None,
         conditional_fp32_ops=None, excluded_sym_names=None):
    """Enable AMP globally (reference: amp.init).

    target_dtype: 'bfloat16' (TPU default; no loss scaling needed) or
    'float16' (classic AMP; pair with a dynamic LossScaler via init_trainer).
    """
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError(f"unsupported AMP target_dtype {target_dtype!r}")
    t_ops = frozenset(target_dtype_ops if target_dtype_ops is not None
                      else lists.TARGET_DTYPE_OPS)
    f_ops = frozenset(fp32_ops if fp32_ops is not None else lists.FP32_OPS)
    st = _amp_dict()
    st["wrap"] = _make_wrap(target_dtype, t_ops, f_ops)
    st["target"] = target_dtype
    # fresh policy token: the eager dispatch cache keys executables on it,
    # so re-init with different lists/dtype can never serve stale casts
    st["epoch"] = _EPOCH()
    st["on"] = True


def disable():
    """Turn AMP off (not in the reference API; useful for tests)."""
    st = _amp_dict()
    st["on"] = False
    st["wrap"] = None
    st["target"] = None
    st["epoch"] = _EPOCH()


@contextmanager
def _cast_scope(target_dtype=_DEFAULT_TARGET, target_dtype_ops=None,
                fp32_ops=None):
    """Scoped AMP: used by TrainStep(dtype=...) so the cast policy is active
    exactly while the model trace runs, without flipping global state for the
    caller's eager code."""
    st = _amp_dict()
    prev = dict(st)
    try:
        init(target_dtype, target_dtype_ops=target_dtype_ops,
             fp32_ops=fp32_ops)
        yield
    finally:
        st.update(prev)


def init_trainer(trainer, loss_scaler=None):
    """Attach dynamic loss scaling to a Gluon Trainer (reference:
    amp.init_trainer).  The trainer's step() gains overflow-skip semantics:
    non-finite scaled gradients skip the update and shrink the scale.

    Composes with the numerical-integrity guard: ``guard.attach`` must
    come AFTER init_trainer (the guard's unified step then owns both the
    verdict and the loss-scale bookkeeping, one host sync total) —
    wrapping an already-guarded trainer would re-split the sync."""
    st = _amp_dict()
    if not st["on"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    if getattr(trainer, "_guard", None) is not None:
        raise MXNetError(
            "amp.init_trainer on a guard-attached trainer: attach order "
            "is amp first, then guard.attach (the guard step subsumes "
            "the AMP overflow sync)")
    if loss_scaler is None:
        loss_scaler = LossScaler(dynamic=(st["target"] == "float16"))
    trainer._amp_loss_scaler = loss_scaler
    trainer._amp_original_scale = trainer._scale
    trainer._amp_unscaled = False

    orig_step = trainer.step

    def amp_step(batch_size, ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        overflow = scaler.has_overflow(trainer._params)
        if not overflow:
            # if unscale() already divided the grads this iteration, don't
            # rescale again
            eff = 1.0 if trainer._amp_unscaled else scaler.loss_scale
            trainer._scale = trainer._amp_original_scale / eff
            orig_step(batch_size, ignore_stale_grad=ignore_stale_grad)
            trainer._scale = trainer._amp_original_scale
        trainer._amp_unscaled = False
        scaler.update_scale(overflow)

    trainer.step = amp_step
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``"""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    s = scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * s for l in loss)
    else:
        yield loss * s


def unscale(trainer):
    """Divide current gradients by the loss scale in place (reference:
    amp.unscale — for gradient clipping between backward and step).  A
    one-shot flag tells the next trainer.step() not to rescale again; the
    dynamic loss scale itself is untouched."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    if getattr(trainer, "_amp_unscaled", False):
        return  # already unscaled this iteration
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null" or p._data is None:
            continue
        for g in p.list_grad():
            g._set(g._get() * inv)
    trainer._amp_unscaled = True


def convert_model(block, target_dtype=_DEFAULT_TARGET,
                  excluded_params=("gamma", "beta", "moving_mean",
                                   "moving_var", "running_mean",
                                   "running_var")):
    """Cast a trained block's parameters to the target dtype for inference
    (reference: amp.convert_model).  Norm-layer params stay fp32."""
    import jax.numpy as jnp

    for name, p in block.collect_params().items():
        if any(name.endswith(sfx) for sfx in excluded_params):
            continue
        if p._data is None:
            continue
        v = p.data()._get()
        if jnp.issubdtype(v.dtype, jnp.floating):
            p.data()._set(v.astype(target_dtype))
            p.dtype = target_dtype
    return block


convert_hybrid_block = convert_model


def list_fp16_ops():
    return list(lists.TARGET_DTYPE_OPS)


def list_fp32_ops():
    return list(lists.FP32_OPS)
