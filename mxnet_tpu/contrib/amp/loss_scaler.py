"""Dynamic loss scaling (reference: python/mxnet/contrib/amp/loss_scaler.py).

Only needed for float16 (5-bit exponent): gradients below ~6e-5 underflow, so
the loss is multiplied by a large scale before backward and gradients divided
by it before the update; on overflow (inf/nan grads) the step is skipped and
the scale halved, and after ``scale_window`` clean steps the scale doubles.
bfloat16 shares fp32's exponent range, so the TPU-default bf16 policy uses a
static scale of 1 (this class still tracks overflow-skip behavior).
"""
from __future__ import annotations

import numpy as np

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0, dynamic=True):
        self.loss_scale = float(init_scale) if dynamic else 1.0
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_scale = min_scale
        self._dynamic = dynamic
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient of ``params`` is non-finite.

        One device-side reduction, ONE blocking host sync: the per-grad
        non-finite counts are summed as lazily-dispatched device ops and
        only the final scalar crosses to the host — the previous
        per-param ``bool(jnp.isfinite(v).all())`` loop paid K blocking
        round-trips on every AMP step.  The verdict is identical: the
        total is > 0 iff any gradient had any non-finite element.

        In a multi-process job the verdict is agreed across all processes
        (logical-or via a host allreduce): a process-local skip would desync
        the replicas' weights and loss scales.

        The fused reduction itself lives in ``guard.nonfinite_total`` —
        the numerical-integrity guard generalized it into the per-step
        sentinel vector, and both callers share ONE source so the AMP
        overflow verdict and the guard's ``nonfinite`` verdict can never
        disagree (the parity test pins this)."""
        import jax

        from ...guard import nonfinite_total

        total = nonfinite_total(params)
        if total is None:
            return False
        if jax.process_count() > 1:
            from ...parallel.collectives import allreduce_hosts

            total = allreduce_hosts(total)
        # THE one designed sync per AMP step: the fused non-finite count
        # crosses to the host exactly once here — mxtpu: noqa[MXT010]
        return bool(np.asarray(total) > 0)

    def state_dict(self):
        """Scale + clean-step counter for exact resume
        (lifecycle.capture_train_state): without it a resumed fp16 run
        restarts at init_scale and the first steps' updates diverge."""
        return {"loss_scale": float(self.loss_scale),
                "unskipped": int(self._unskipped)}

    def load_state_dict(self, state):
        self.loss_scale = float(state["loss_scale"])
        self._unskipped = int(state["unskipped"])

    def update_scale(self, overflow):
        """Adjust the scale after a step; returns True if the step should be
        skipped (overflow observed)."""
        if not self._dynamic:
            return bool(overflow)
        if overflow:
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
            return True
        self._unskipped += 1
        if self._unskipped >= self._scale_window:
            self.loss_scale = float(
                min(np.finfo(np.float32).max,
                    self.loss_scale * self._scale_factor))
            self._unskipped = 0
        return False
