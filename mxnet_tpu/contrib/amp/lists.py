"""AMP op lists (reference: python/mxnet/contrib/amp/lists/symbol_fp16.py).

On TPU the low-precision target is **bfloat16** (same exponent range as fp32,
so no loss scaling is required for the default policy), but the classic fp16
policy with dynamic loss scaling is also supported for parity.

- ``TARGET_DTYPE_OPS``: MXU-bound ops whose float inputs are cast DOWN to the
  target dtype (matmul/conv FLOPs at 2x rate, halved HBM traffic).
- ``FP32_OPS``: numerically sensitive ops whose inputs are cast UP to fp32
  (softmax/exp/log reductions, losses).
- everything else runs in whatever dtype arrives (jnp type promotion handles
  mixed inputs; the norm layers internally accumulate statistics in fp32 —
  see ops/nn.py batch_norm/layer_norm).
"""

# ops that should run on the MXU in the low-precision target dtype
TARGET_DTYPE_OPS = [
    "Convolution",
    "Deconvolution",
    "FullyConnected",
    "dot",
    "batch_dot",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "_contrib_interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt",
    "_contrib_flash_attention",
    "RNN",
]

# numerically sensitive ops pinned to fp32
FP32_OPS = [
    "softmax",
    "log_softmax",
    "softmin",
    "SoftmaxOutput",
    "SoftmaxActivation",
    "softmax_cross_entropy",
    "CTCLoss",
    "LRN",
    "L2Normalization",
    "InstanceNorm",
    "exp",
    "log",
    "log2",
    "log10",
    "log1p",
    "expm1",
    "power",
    "norm",
    "mean",
    "sum",
    "nansum",
    "prod",
    "nanprod",
    "cumsum",
    "erf",
    "erfinv",
    "gamma",
    "gammaln",
    "MakeLoss",
    "LinearRegressionOutput",
    "LogisticRegressionOutput",
    "MAERegressionOutput",
]

# kept for API parity with the reference lists module
FP16_FP32_OPS = []  # "run in either" — we leave input dtypes untouched
