"""AMP — automatic mixed precision for TPU (bf16-first).

Reference: python/mxnet/contrib/amp/ (SURVEY.md §3.5 contrib: AMP).
"""
from .amp import (init, disable, init_trainer, scale_loss, unscale,
                  convert_model, convert_hybrid_block, list_fp16_ops,
                  list_fp32_ops, _cast_scope)
from .loss_scaler import LossScaler
from . import lists  # noqa: F401

__all__ = ["init", "disable", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "list_fp16_ops",
           "list_fp32_ops", "LossScaler"]
