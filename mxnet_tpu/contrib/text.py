"""Text utilities: token counting, vocabulary, token embeddings.

Reference: ``python/mxnet/contrib/text/{utils,vocab,embedding}.py``
(SURVEY.md §3.5 contrib misc).  Pretrained-embedding *downloads* are
unavailable offline — ``CustomEmbedding`` loads any local
``token<space>v1 v2 …`` file, which is the same code path the reference's
GloVe/fastText classes use after their download step.
"""
from __future__ import annotations

import collections
import re

from ..base import MXNetError

__all__ = ["count_tokens_from_str", "Vocabulary", "TokenEmbedding",
           "CustomEmbedding"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens (reference: contrib.text.utils.count_tokens_from_str)."""
    source_str = re.sub(f"({token_delim})|({seq_delim})", " ", source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(source_str.split())
    return counter


class Vocabulary:
    """Indexed vocabulary (reference: contrib.text.vocab.Vocabulary).

    Index 0 is the unknown token; reserved tokens follow, then counted
    tokens by descending frequency (ties broken alphabetically, matching
    the reference sort).
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if len(set(reserved_tokens)) != len(reserved_tokens) or \
                    unknown_token in reserved_tokens:
                raise MXNetError("reserved_tokens must be unique and must "
                                 "not contain unknown_token")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens or [])
        self._idx_to_token = [unknown_token] + self._reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            skip = set(self._idx_to_token)
            for tok, freq in pairs:
                if freq >= min_freq and tok not in skip:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idx = [indices] if single else indices
        out = []
        for i in idx:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out


class TokenEmbedding(Vocabulary):
    """Vocabulary + vector per token (reference:
    contrib.text.embedding._TokenEmbedding).  The unknown token maps to
    ``init_unknown_vec`` (zeros by default)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_file(self, path, elem_delim=" ", encoding="utf8",
                             init_unknown_vec=None):
        import numpy as np

        from .. import ndarray as nd

        rows = []
        with open(path, encoding=encoding) as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                try:
                    rows.append((parts[0],
                                 np.asarray([float(v) for v in parts[1:]],
                                            "f")))
                except ValueError:
                    continue
        if not rows:
            raise MXNetError(f"no vectors found in {path}")
        # the embedding dim is the majority row length — robust to a
        # "count dim" header line (its length differs from the data rows)
        # including the 1-D-embedding case the old >1-values guard broke
        import collections as _collections

        vec_len = _collections.Counter(
            len(v) for _, v in rows).most_common(1)[0][0]
        vecs = {tok: v for tok, v in rows if len(v) == vec_len}
        if not vecs:
            raise MXNetError(f"no vectors found in {path}")
        self._vec_len = vec_len
        # extend the index with every token in the file
        for tok in vecs:
            if tok not in self._token_to_idx:
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)
        mat = np.zeros((len(self), vec_len), "f")
        for tok, v in vecs.items():
            mat[self._token_to_idx[tok]] = v
        unk = (init_unknown_vec or (lambda shape: np.zeros(shape, "f")))
        mat[0] = np.asarray(unk((vec_len,)), "f").reshape(vec_len)
        self._idx_to_vec = nd.array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        from .. import ndarray as nd

        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t, 0)
            if i == 0 and lower_case_backup:
                i = self._token_to_idx.get(t.lower(), 0)
            idx.append(i)
        rows = nd.take(self._idx_to_vec, nd.array(idx, dtype="int32"), axis=0)
        return rows[0] if single else rows

    def update_token_vectors(self, tokens, new_vectors):
        from .. import ndarray as nd
        import numpy as np

        toks = [tokens] if isinstance(tokens, str) else tokens
        mat = self._idx_to_vec.asnumpy().copy()
        vals = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors, "f")
        vals = vals.reshape((len(toks), self._vec_len))
        for t, v in zip(toks, vals):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is unknown; only existing "
                                 "tokens can be updated")
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(mat)

    def __getitem__(self, tokens):
        return self.get_vecs_by_tokens(tokens)


class CustomEmbedding(TokenEmbedding):
    """Embedding loaded from a local ``token v1 v2 …`` text file
    (reference: contrib.text.embedding.CustomEmbedding; the GloVe/fastText
    subclasses differ only in their download step, which offline builds
    replace with a local file path)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=None, vocabulary=None, **kwargs):
        if vocabulary is not None:
            self._unknown_token = vocabulary.unknown_token
            self._reserved_tokens = list(vocabulary.reserved_tokens)
            self._idx_to_token = list(vocabulary.idx_to_token)
            self._token_to_idx = dict(vocabulary.token_to_idx)
            self._vec_len = 0
            self._idx_to_vec = None
        else:
            super().__init__(**kwargs)
        self._load_embedding_file(pretrained_file_path, elem_delim, encoding,
                                  init_unknown_vec)
