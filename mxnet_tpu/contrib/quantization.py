"""Post-training INT8 quantization for Gluon models.

Reference: ``python/mxnet/contrib/quantization.py`` (``quantize_model`` /
``quantize_net``: graph pass replacing FC/conv with quantized kernels +
naive-minmax or KL-entropy calibration — SURVEY.md §3.2 quantization row).

TPU-native shape: instead of a symbol-graph rewrite, Dense/Conv2D children
are swapped for Quantized blocks whose forward runs the fused int8 ops
(``ops/quantization_ops.py``: int8 x int8 -> int32 on the MXU, fp32
epilogue).  Weights are per-output-channel symmetric int8; activations use
per-tensor calibrated ranges (naive min/max or KL-optimal thresholds, the
same two calib_modes the reference ships).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv2D",
           "optimal_threshold_kl"]


def optimal_threshold_kl(data, num_bins=1001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| for symmetric int8 (reference:
    _LayerHistogramCollector + _get_optimal_threshold, the TensorRT-style
    entropy calibration).  Returns the threshold t: [-t, t] is quantized."""
    a = _np.abs(_np.asarray(data, dtype="float64").ravel())
    amax = float(a.max()) if a.size else 0.0
    if amax <= 0:
        return 1e-8
    hist, edges = _np.histogram(a, bins=num_bins, range=(0.0, amax))
    total = hist.sum()
    if total == 0:
        return amax

    best_t, best_kl = amax, _np.inf
    # candidate thresholds sweep the top half of the histogram
    for i in range(num_quantized_bins, num_bins + 1,
                   max((num_bins - num_quantized_bins) // 64, 1)):
        sliced = hist[:i].astype("float64")
        # P: the reference distribution with clipped mass folded into the
        # last bin; Q: the UNCLIPPED slice quantized to int8 resolution and
        # expanded back.  (Building Q from the clipped P would hide the
        # clipping error and the search would collapse to tiny thresholds.)
        p = sliced.copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        q = _np.zeros(i, dtype="float64")
        factor = i / num_quantized_bins
        for j in range(num_quantized_bins):
            lo = int(_np.floor(j * factor))
            hi = max(int(_np.ceil((j + 1) * factor)), lo + 1)
            hi = min(hi, i)
            mass = sliced[lo:hi].sum()
            nz = (sliced[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = _np.where(sliced[lo:hi] > 0, mass / nz, 0.0)
        pn = p / p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        qn = q / qs
        # D_KL(P||Q) with the standard smoothing for q=0, p>0 bins
        eps = 1e-10
        mask = pn > 0
        kl = float(_np.sum(pn[mask] * _np.log(pn[mask] /
                                              _np.maximum(qn[mask], eps))))
        if kl < best_kl:
            best_kl = kl
            best_t = edges[i] if i < len(edges) else amax
    return float(best_t)


class _Calib:
    """Per-layer activation-range collector."""

    def __init__(self, mode):
        self.mode = mode
        self.minmax = {}
        self.samples = {}
        self.last_fired = {}  # layer name -> last firing tick (exec order)
        self._tick = 0

    def observe(self, key, arr):
        self._tick += 1
        self.last_fired[key] = self._tick
        a = _np.asarray(arr)
        lo, hi = float(a.min()), float(a.max())
        if key in self.minmax:
            plo, phi = self.minmax[key]
            self.minmax[key] = (min(lo, plo), max(hi, phi))
        else:
            self.minmax[key] = (lo, hi)
        if self.mode == "entropy":
            self.samples.setdefault(key, []).append(
                a.ravel()[:: max(a.size // 8192, 1)].copy())

    def range_of(self, key):
        lo, hi = self.minmax[key]
        if self.mode == "entropy":
            t = optimal_threshold_kl(_np.concatenate(self.samples[key]))
            return -t, t
        amax = max(abs(lo), abs(hi))
        return -amax, amax


def _quantize_weight(w):
    """Per-output-channel symmetric int8: returns (int8 weight, fp32
    scales of shape (out_channels,))."""
    w = _np.asarray(w, dtype="float32")
    flat = w.reshape(w.shape[0], -1)
    amax = _np.maximum(_np.abs(flat).max(axis=1), 1e-12)
    scale = amax / 127.0
    q = _np.clip(_np.round(flat / scale[:, None]), -127, 127).astype("int8")
    return q.reshape(w.shape), scale.astype("float32")


def _import_hybrid_block():
    from ..gluon.block import HybridBlock

    return HybridBlock


class _QuantizedLayer:
    """Shared state for int8 layers: quantized weight, per-channel scales,
    fp32 bias, calibrated activation range, optional fused activation."""

    def _setup(self, wq, wscale, bias, act_range, act):
        from .. import ndarray as nd

        # constants (not plain attributes) so collect_params/save_parameters
        # serialize the quantized model — including the calibrated
        # activation range — like any other Gluon net
        self.weight_quantized = self.params.get_constant(
            "weight_quantized", nd.array(wq.astype("float32")).astype("int8"))
        self.weight_scale = self.params.get_constant(
            "weight_scale", nd.array(wscale))
        self.act_range = self.params.get_constant(
            "act_range", nd.array(_np.asarray(act_range, dtype="float32")))
        self._has_bias = bias is not None
        if self._has_bias:
            self.bias_fp32 = self.params.get_constant(
                "bias_fp32", nd.array(bias))
        for p in self._params.values():
            p.initialize()
        self.act = act  # Block.__setattr__ registers it as a child

    @property
    def _wq(self):
        return self.weight_quantized.data()

    def __repr__(self):
        lo, hi = self.act_range.data().asnumpy()
        return (f"{type(self).__name__}(act_range=({lo:.4g}, {hi:.4g}))")


def _define_layers():
    HybridBlock = _import_hybrid_block()

    class QuantizedDense(_QuantizedLayer, HybridBlock):
        """INT8 Dense (reference: quantized FC kernel)."""

        def __init__(self, wq, wscale, bias, act_range, act=None,
                     flatten=True, **kw):
            HybridBlock.__init__(self, **kw)
            self._flatten = flatten
            self._setup(wq, wscale, bias, act_range, act)

        @classmethod
        def from_dense(cls, orig, act_range):
            wq, wscale = _quantize_weight(orig.weight.data().asnumpy())
            bias = orig.bias.data().asnumpy() if orig.bias is not None \
                else None
            return cls(wq, wscale, bias, act_range, act=orig.act,
                       flatten=orig._flatten, prefix=orig.prefix + "int8_")

        def hybrid_forward(self, F, x, weight_quantized, weight_scale,
                           act_range, bias_fp32=None):
            args = [x, weight_quantized, weight_scale, act_range]
            if bias_fp32 is not None:
                args.append(bias_fp32)
            y = F._contrib_quantized_fully_connected(
                *args, no_bias=bias_fp32 is None, flatten=self._flatten)
            return self.act(y) if self.act is not None else y

    class QuantizedConv2D(_QuantizedLayer, HybridBlock):
        """INT8 NCHW convolution (reference: quantized conv kernel)."""

        def __init__(self, wq, wscale, bias, act_range, conv_kwargs,
                     act=None, **kw):
            HybridBlock.__init__(self, **kw)
            self._conv_kwargs = dict(conv_kwargs)
            self._setup(wq, wscale, bias, act_range, act)

        @classmethod
        def from_conv(cls, orig, act_range):
            wq, wscale = _quantize_weight(orig.weight.data().asnumpy())
            bias = orig.bias.data().asnumpy() if orig.bias is not None \
                else None
            return cls(wq, wscale, bias, act_range, orig._kwargs,
                       act=orig.act, prefix=orig.prefix + "int8_")

        def hybrid_forward(self, F, x, weight_quantized, weight_scale,
                           act_range, bias_fp32=None):
            kw = self._conv_kwargs
            args = [x, weight_quantized, weight_scale, act_range]
            if bias_fp32 is not None:
                args.append(bias_fp32)
            y = F._contrib_quantized_conv(
                *args, kernel=kw["kernel"], stride=kw["stride"],
                pad=kw["pad"], dilate=kw["dilate"],
                num_filter=kw["num_filter"], num_group=kw["num_group"],
                no_bias=bias_fp32 is None)
            return self.act(y) if self.act is not None else y

    return QuantizedDense, QuantizedConv2D


QuantizedDense, QuantizedConv2D = _define_layers()


def _target_layers(block, exclude):
    """(parent, child_key, layer) for every quantizable descendant.

    Conv2D with a non-NCHW layout stays fp32 (the int8 conv op lowers
    NCHW dimension numbers only)."""
    from ..gluon import nn

    out = []
    for key, child in block._children.items():
        is_conv = type(child).__name__ == "Conv2D" and \
            child._kwargs.get("layout") in (None, "NCHW")
        if isinstance(child, nn.Dense) or is_conv:
            if child.name not in exclude:
                out.append((block, key, child))
        else:
            out.extend(_target_layers(child, exclude))
    return out


def quantize_net(net, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=(),
                 quantize_mode="smart", num_calib_batches=None, logger=None):
    """Post-training-quantize a Gluon net in place and return it.

    calib_data: iterable of input batches (NDArray/ndarray) for activation
    range calibration; calib_mode 'naive' (min/max) or 'entropy' (KL).
    Dense and Conv2D children are replaced by int8 blocks; everything else
    (BN, pooling, activations) stays fp32 — the reference's partitioning
    makes the same split.  quantize_mode 'smart' (default, like the
    reference) keeps the final output layer fp32 — saturating the logits
    layer is what flips confident predictions; 'full' quantizes all."""
    from .. import autograd
    from ..ndarray.ndarray import NDArray
    from ..ndarray import array

    if quantized_dtype != "int8":
        raise MXNetError("only int8 is supported")
    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    if quantize_mode not in ("smart", "full"):
        raise MXNetError(f"unknown quantize_mode {quantize_mode!r}")
    if calib_data is None:
        raise MXNetError("calibration data is required (post-training "
                         "quantization observes activation ranges)")

    targets = _target_layers(net, set(exclude_layers))
    if not targets:
        raise MXNetError("no quantizable Dense/Conv2D layers found")

    # 1. calibration pass: observe each target layer's INPUT range.
    # Hybridized execution would bypass the child hooks (the cached jit
    # runs as one program), so calibration runs the eager path; the
    # caller's hybridization state is restored afterwards (also on error).
    def _collect_active(b, out):
        if hasattr(b, "_active"):
            out.append((b, b._active))
        for c in b._children.values():
            _collect_active(c, out)

    prev_active = []
    _collect_active(net, prev_active)

    def _restore_hybridization():
        for b, active in prev_active:
            if active:
                b.hybridize(True)

    net.hybridize(False)
    calib = _Calib(calib_mode)
    handles = []
    try:
        for _, _, layer in targets:
            handles.append(layer.register_forward_pre_hook(
                (lambda lyr: lambda blk, inputs:
                 calib.observe(lyr.name, inputs[0].asnumpy()))(layer)))
        with autograd.pause():
            for i, batch in enumerate(calib_data):
                if num_calib_batches is not None and i >= num_calib_batches:
                    break
                x = batch if isinstance(batch, NDArray) else array(batch)
                net(x)
        missing = [l.name for _, _, l in targets
                   if l.name not in calib.minmax]
        if missing:
            raise MXNetError(
                f"calibration never reached layers {missing}; pass "
                "calib_data that exercises the whole net")
    except Exception:
        _restore_hybridization()
        raise
    finally:
        for h in handles:
            h.detach()
    if quantize_mode == "smart" and len(targets) > 1:
        # keep the OUTPUT layer fp32 — decided by execution order (hook
        # firing), not registration order, so custom blocks that register
        # children out of call order still protect their logits layer
        out_name = max((l.name for _, _, l in targets),
                       key=lambda nm: calib.last_fired[nm])
        targets = [t for t in targets if t[2].name != out_name]

    # 2. swap in quantized blocks
    for parent, key, layer in targets:
        rng = calib.range_of(layer.name)
        q = QuantizedDense.from_dense(layer, rng) \
            if type(layer).__name__ == "Dense" \
            else QuantizedConv2D.from_conv(layer, rng)
        parent._children[key] = q
        for attr, val in list(vars(parent).items()):
            if val is layer:
                object.__setattr__(parent, attr, q)
    # restore the caller's hybridization state (new quantized blocks adopt
    # their parent's state) and invalidate caches up the tree
    _restore_hybridization()

    def _bump(b):
        b._bump_cache_version()
        for c in b._children.values():
            _bump(c)

    _bump(net)
    return net
