"""Contrib namespace (reference: python/mxnet/contrib/ — SURVEY.md §3.5)."""
from . import amp  # noqa: F401
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import svrg_optimization  # noqa: F401
