"""ONNX interop (reference: ``python/mxnet/contrib/onnx/`` — SURVEY.md
§3.5 contrib row): ``export_model`` (mx2onnx) and ``import_model`` /
``import_to_gluon`` (onnx2mx), self-contained over a minimal protobuf
wire codec (this environment has no onnx pip package)."""
from .mx2onnx import export_model
from .onnx2mx import import_model, import_to_gluon

__all__ = ["export_model", "import_model", "import_to_gluon"]
