"""ONNX -> Symbol import.

Reference: ``python/mxnet/contrib/onnx/onnx2mx/import_model.py`` +
``_import_helper.py`` op map (SURVEY.md §3.5 contrib onnx row): returns
``(sym, arg_params, aux_params)`` ready for Module/SymbolBlock.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import ir

__all__ = ["import_model", "import_to_gluon"]


def _pool_attrs(a):
    kernel = tuple(a.get("kernel_shape", (1, 1)))
    pads = a.get("pads")
    pad = tuple(pads[:len(kernel)]) if pads else (0,) * len(kernel)
    return kernel, tuple(a.get("strides", (1,) * len(kernel))), pad


class _Importer:
    def __init__(self):
        import mxnet_tpu as mx

        self.sym = mx.sym
        self.nd = mx.nd
        self.tensors = {}      # onnx name -> Symbol
        self.arg_params = {}
        self.aux_params = {}
        self.initializer_data = {}
        self.unproduced = set()  # declared-but-unsupported node outputs

    def var(self, name):
        if name in self.unproduced:
            raise MXNetError(
                f"ONNX tensor {name!r} is a secondary node output this "
                "importer does not produce (e.g. Dropout mask / BN "
                "training stats) but the graph consumes it")
        if name not in self.tensors:
            self.tensors[name] = self.sym.var(name)
        return self.tensors[name]

    # -- op handlers -------------------------------------------------------
    def _conv(self, node, a, name):
        ins = node["input"]
        kernel, stride, pad = _pool_attrs(a)
        w = self.initializer_data.get(ins[1])
        num_filter = int(w.shape[0]) if w is not None else 0
        return self.sym.Convolution(
            *[self.var(i) for i in ins], kernel=kernel, stride=stride,
            pad=pad, dilate=tuple(a.get("dilations", (1,) * len(kernel))),
            num_filter=num_filter, num_group=int(a.get("group", 1)),
            no_bias=len(ins) < 3, name=name)

    def _gemm(self, node, a, name):
        ins = node["input"]
        if a.get("transA"):
            raise MXNetError("Gemm with transA has no FC mapping")
        w = self.initializer_data.get(ins[1])
        if w is None:
            raise MXNetError("Gemm needs a constant B (weight) input")
        if not a.get("transB", 0):
            # FC wants (out, in): transpose the initializer once at import
            w = _np.ascontiguousarray(w.T)
        alpha = float(a.get("alpha", 1.0))
        if alpha != 1.0:  # fold into the weight
            w = w * alpha
        if w is not self.initializer_data.get(ins[1]):
            self.initializer_data[ins[1]] = w
            self.arg_params[ins[1]] = self.nd.array(w)
        beta = float(a.get("beta", 1.0))
        if len(ins) > 2 and beta != 1.0:
            b = self.initializer_data.get(ins[2])
            if b is None:
                raise MXNetError("Gemm with beta != 1 needs a constant C")
            b = b * beta
            self.initializer_data[ins[2]] = b
            self.arg_params[ins[2]] = self.nd.array(b)
        return self.sym.FullyConnected(
            *[self.var(i) for i in ins], num_hidden=int(w.shape[0]),
            no_bias=len(ins) < 3, flatten=False, name=name)

    def _bn(self, node, a, name):
        ins = node["input"]
        # stats are aux states; rename when the source name lacks the
        # suffix the aux-classification convention keys on
        data, scale, bias, mean, var = ins
        for old, suffix in ((mean, "running_mean"), (var, "running_var")):
            if old in self.arg_params:
                if old.endswith(suffix):
                    self.aux_params[old] = self.arg_params.pop(old)
                else:
                    new = f"{name}_{suffix}"
                    self.aux_params[new] = self.arg_params.pop(old)
                    self.tensors[new] = self.sym.var(new)
                    self.tensors[old] = self.tensors[new]
        return self.sym.BatchNorm(
            self.var(data), self.var(scale), self.var(bias),
            self.var(mean), self.var(var),
            eps=float(a.get("epsilon", 1e-5)),
            momentum=float(a.get("momentum", 0.9)), fix_gamma=False,
            use_global_stats=True, name=name)

    def _pool(self, op):
        def h(self_, node, a, name):
            ins = node["input"]
            if op.startswith("Global"):
                return self_.sym.Pooling(
                    self_.var(ins[0]), global_pool=True,
                    pool_type="max" if "Max" in op else "avg", name=name)
            kernel, stride, pad = _pool_attrs(a)
            return self_.sym.Pooling(
                self_.var(ins[0]), kernel=kernel, stride=stride, pad=pad,
                pool_type="max" if op == "MaxPool" else "avg",
                count_include_pad=bool(a.get("count_include_pad", 1)),
                name=name)

        return h

    def _reshape(self, node, a, name):
        ins = node["input"]
        shape = self.initializer_data.get(ins[1])
        if shape is None:
            raise MXNetError("Reshape needs a constant shape input")
        self.arg_params.pop(ins[1], None)
        return self.sym.reshape(self.var(ins[0]),
                                shape=tuple(int(s) for s in shape), name=name)

    def _unary(self, mx_op, **fixed):
        def h(self_, node, a, name):
            return getattr(self_.sym, mx_op)(
                self_.var(node["input"][0]), name=name, **fixed)

        return h

    def _binary(self, mx_op):
        def h(self_, node, a, name):
            i = node["input"]
            return getattr(self_.sym, mx_op)(
                self_.var(i[0]), self_.var(i[1]), name=name)

        return h

    def _axis_op(self, mx_op, attr="axis", default=-1, mx_attr="axis"):
        def h(self_, node, a, name):
            return getattr(self_.sym, mx_op)(
                self_.var(node["input"][0]), name=name,
                **{mx_attr: int(a.get(attr, default))})

        return h

    def convert(self, node):
        op = node["op_type"]
        a = ir.attrs_of(node)
        name = node.get("name") or node["output"][0]
        handlers = {
            "Conv": _Importer._conv,
            "Gemm": _Importer._gemm,
            "BatchNormalization": _Importer._bn,
            "Reshape": _Importer._reshape,
            "MaxPool": self._pool("MaxPool"),
            "AveragePool": self._pool("AveragePool"),
            "GlobalMaxPool": self._pool("GlobalMaxPool"),
            "GlobalAveragePool": self._pool("GlobalAveragePool"),
            "Relu": self._unary("relu"),
            "Sigmoid": self._unary("sigmoid"),
            "Tanh": self._unary("tanh"),
            "Softsign": self._unary("softsign"),
            "Identity": None,
            "Flatten": self._unary("Flatten"),
            "Add": self._binary("broadcast_add"),
            "Sub": self._binary("broadcast_sub"),
            "Mul": self._binary("broadcast_mul"),
            "Div": self._binary("broadcast_div"),
            "MatMul": self._binary("dot"),
            "Softmax": self._axis_op("softmax"),
            "LogSoftmax": self._axis_op("log_softmax"),
            "Transpose": None,  # special below
            "Concat": None,
            "LeakyRelu": None,
            "Elu": None,
            "Dropout": None,
        }
        if op == "Transpose":
            out = self.sym.transpose(self.var(node["input"][0]),
                                     axes=tuple(a.get("perm", ())) or None,
                                     name=name)
        elif op == "Concat":
            out = self.sym.concat(*[self.var(i) for i in node["input"]],
                                  dim=int(a.get("axis", 1)), name=name)
        elif op == "LeakyRelu":
            out = self.sym.LeakyReLU(self.var(node["input"][0]),
                                     act_type="leaky",
                                     slope=float(a.get("alpha", 0.01)),
                                     name=name)
        elif op == "Elu":
            out = self.sym.LeakyReLU(self.var(node["input"][0]),
                                     act_type="elu",
                                     slope=float(a.get("alpha", 1.0)),
                                     name=name)
        elif op == "Dropout":
            out = self.var(node["input"][0])
        elif op == "Identity":
            out = self.var(node["input"][0])
        elif op in handlers and handlers[op] is not None:
            out = handlers[op](self, node, a, name)
        else:
            raise MXNetError(f"ONNX op {op!r} has no import mapping")
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        main = node["output"]
        for o_name, o_sym in zip(main, outs):
            self.tensors[o_name] = o_sym
        # secondary outputs we did not produce (Dropout mask, BN training
        # stats): legal to DECLARE but an error to consume — record them
        # so var() fails loudly instead of silently making a free input
        for o_name in main[len(outs):]:
            self.unproduced.add(o_name)
        return out


def import_model(model_file):
    """Parse an .onnx file -> (sym, arg_params, aux_params)."""
    with open(model_file, "rb") as f:
        data = f.read()
    model = ir.parse_model(data)
    graph = model.get("graph")
    if graph is None:
        raise MXNetError(f"{model_file}: no graph in ONNX model")

    imp = _Importer()
    for t in graph.get("initializer", []):
        arr = ir.tensor_to_numpy(t)
        imp.initializer_data[t["name"]] = arr
        imp.arg_params[t["name"]] = imp.nd.array(arr)
    for node in graph.get("node", []):
        imp.convert(node)
    outs = []
    for vi in graph.get("output", []):
        name = vi["name"]
        if name not in imp.tensors:
            raise MXNetError(f"ONNX output {name!r} was never produced")
        outs.append(imp.tensors[name])
    sym = outs[0] if len(outs) == 1 else imp.sym.Group(outs)
    return sym, imp.arg_params, imp.aux_params


def import_to_gluon(model_file, ctx=None):
    """Parse an .onnx file into a SymbolBlock (reference:
    onnx_mxnet.import_to_gluon)."""
    import mxnet_tpu as mx
    from ...gluon.block import SymbolBlock

    sym, arg_params, aux_params = import_model(model_file)
    graph = ir.parse_model(open(model_file, "rb").read())["graph"]
    # older exporters list initializers in graph.input too
    # (keep_initializers_as_inputs): only initializer-free names are
    # runtime inputs
    init_names = {t["name"] for t in graph.get("initializer", [])}
    inputs = [mx.sym.var(vi["name"]) for vi in graph.get("input", [])
              if vi["name"] not in init_names]
    params = dict(arg_params)
    params.update(aux_params)
    return SymbolBlock(sym, inputs, params=params)
