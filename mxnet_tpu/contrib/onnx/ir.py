"""ONNX IR subset: schemas (field numbers per the public onnx.proto3) +
builder/reader helpers over the wire codec."""
from __future__ import annotations

import numpy as _np

from . import wire

# -- TensorProto.DataType (public enum values) ------------------------------
DT = {"float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
      "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
      "uint32": 12, "uint64": 13, "bfloat16": 16}
DT_INV = {v: k for k, v in DT.items()}

# -- message schemas: {field: (name, kind, repeated)} -----------------------
TENSOR = {
    1: ("dims", "int", True),
    2: ("data_type", "int", False),
    4: ("float_data", "float", True),
    5: ("int32_data", "int", True),
    7: ("int64_data", "int", True),
    8: ("name", "string", False),
    9: ("raw_data", "bytes", False),
    10: ("double_data", "double", True),
}
ATTRIBUTE = {
    1: ("name", "string", False),
    2: ("f", "float", False),
    3: ("i", "int", False),
    4: ("s", "bytes", False),
    5: ("t", TENSOR, False),
    7: ("floats", "float", True),
    8: ("ints", "int", True),
    9: ("strings", "bytes", True),
    20: ("type", "int", False),
}
# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8

DIMENSION = {1: ("dim_value", "int", False), 2: ("dim_param", "string", False)}
SHAPE = {1: ("dim", DIMENSION, True)}
TENSOR_TYPE = {1: ("elem_type", "int", False), 2: ("shape", SHAPE, False)}
TYPE = {1: ("tensor_type", TENSOR_TYPE, False)}
VALUE_INFO = {1: ("name", "string", False), 2: ("type", TYPE, False)}
NODE = {
    1: ("input", "string", True),
    2: ("output", "string", True),
    3: ("name", "string", False),
    4: ("op_type", "string", False),
    5: ("attribute", ATTRIBUTE, True),
    7: ("domain", "string", False),
}
GRAPH = {
    1: ("node", NODE, True),
    2: ("name", "string", False),
    5: ("initializer", TENSOR, True),
    11: ("input", VALUE_INFO, True),
    12: ("output", VALUE_INFO, True),
    13: ("value_info", VALUE_INFO, True),
}
OPSET = {1: ("domain", "string", False), 2: ("version", "int", False)}
MODEL = {
    1: ("ir_version", "int", False),
    2: ("producer_name", "string", False),
    3: ("producer_version", "string", False),
    5: ("model_version", "int", False),
    7: ("graph", GRAPH, False),
    8: ("opset_import", OPSET, True),
}

OPSET_VERSION = 13
IR_VERSION = 8


# -- builders ---------------------------------------------------------------
def make_tensor(name, arr):
    arr = _np.ascontiguousarray(arr)
    dt = DT.get(str(arr.dtype))
    if dt is None:
        raise ValueError(f"dtype {arr.dtype} has no ONNX mapping")
    return {"name": name, "dims": list(arr.shape), "data_type": dt,
            "raw_data": arr.tobytes()}


def tensor_to_numpy(t):
    import ml_dtypes  # bundled with jax; provides the bfloat16 numpy dtype

    name = DT_INV[t["data_type"]]
    dtype = _np.dtype(ml_dtypes.bfloat16) if name == "bfloat16" \
        else _np.dtype(name)
    dims = t.get("dims", [])
    if "raw_data" in t and t["raw_data"]:
        return _np.frombuffer(t["raw_data"], dtype=dtype).reshape(dims).copy()
    if name in ("float16", "bfloat16") and t.get("int32_data"):
        # per onnx.proto, 16-bit floats in int32_data carry uint16 BIT
        # PATTERNS — reinterpret, never value-cast
        bits = _np.asarray(t["int32_data"], dtype="int32").astype("uint16")
        return bits.view(dtype).reshape(dims)
    for field, cast in (("float_data", "float32"), ("int64_data", "int64"),
                        ("int32_data", "int32"), ("double_data", "float64")):
        if t.get(field):
            return _np.asarray(t[field], dtype=cast).astype(dtype).reshape(dims)
    return _np.zeros(dims, dtype=dtype)


def make_attr(name, value):
    if isinstance(value, bool):
        return {"name": name, "type": AT_INT, "i": int(value)}
    if isinstance(value, int):
        return {"name": name, "type": AT_INT, "i": value}
    if isinstance(value, float):
        return {"name": name, "type": AT_FLOAT, "f": value}
    if isinstance(value, str):
        return {"name": name, "type": AT_STRING, "s": value.encode()}
    if isinstance(value, _np.ndarray):
        return {"name": name, "type": AT_TENSOR, "t": make_tensor(name, value)}
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, _np.integer)) for v in value):
            return {"name": name, "type": AT_INTS,
                    "ints": [int(v) for v in value]}
        return {"name": name, "type": AT_FLOATS,
                "floats": [float(v) for v in value]}
    raise ValueError(f"attr {name}: unsupported value {value!r}")


def attr_value(a):
    t = a.get("type")
    if t == AT_INT:
        return a.get("i", 0)
    if t == AT_FLOAT:
        return a.get("f", 0.0)
    if t == AT_STRING:
        return a.get("s", b"").decode()
    if t == AT_INTS:
        return list(a.get("ints", []))
    if t == AT_FLOATS:
        return list(a.get("floats", []))
    if t == AT_TENSOR:
        return tensor_to_numpy(a["t"])
    return None


def attrs_of(node):
    return {a["name"]: attr_value(a) for a in node.get("attribute", [])}


def make_node(op_type, inputs, outputs, name=None, **attrs):
    n = {"op_type": op_type, "input": list(inputs), "output": list(outputs),
         "name": name or outputs[0]}
    if attrs:
        n["attribute"] = [make_attr(k, v) for k, v in attrs.items()
                          if v is not None]
    return n


def make_value_info(name, shape, dtype="float32"):
    return {"name": name, "type": {"tensor_type": {
        "elem_type": DT[str(dtype)],
        "shape": {"dim": [
            {"dim_value": int(d)} if d else {"dim_param": "N"}
            for d in shape]}}}}


def make_model(graph, producer="mxnet_tpu"):
    return {"ir_version": IR_VERSION, "producer_name": producer,
            "producer_version": "0.1", "model_version": 1, "graph": graph,
            "opset_import": [{"domain": "", "version": OPSET_VERSION}]}


def serialize_model(model):
    return wire.encode(model, MODEL)


def parse_model(data):
    return wire.decode(data, MODEL)
