"""Minimal protobuf wire-format codec (no protobuf/onnx dependency).

Reference role: the serialization layer under
``python/mxnet/contrib/onnx`` (which uses the onnx pip package; this
environment has none, so the ONNX IR subset is encoded/decoded directly —
field numbers follow the public onnx.proto3 spec, so files interoperate
with standard ONNX tooling).

Schema model: a message schema is ``{field_number: (name, kind, repeated)}``
with kind in {'int','float','double','bytes','string',sub-schema-dict}.
Messages are plain dicts; repeated fields are lists.
"""
from __future__ import annotations

import struct

__all__ = ["encode", "decode"]


def _enc_varint(v, out):
    if v < 0:
        v &= (1 << 64) - 1  # two's-complement 64-bit like protobuf
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _dec_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if result >= 1 << 63:  # negative int64
        result -= 1 << 64
    return result, pos


def _enc_field(num, kind, value, out):
    if isinstance(kind, dict):  # nested message
        payload = encode(value, kind)
        _enc_varint((num << 3) | 2, out)
        _enc_varint(len(payload), out)
        out.extend(payload)
    elif kind == "int":
        _enc_varint((num << 3) | 0, out)
        _enc_varint(int(value), out)
    elif kind == "float":
        _enc_varint((num << 3) | 5, out)
        out.extend(struct.pack("<f", float(value)))
    elif kind == "double":
        _enc_varint((num << 3) | 1, out)
        out.extend(struct.pack("<d", float(value)))
    elif kind in ("bytes", "string"):
        data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        _enc_varint((num << 3) | 2, out)
        _enc_varint(len(data), out)
        out.extend(data)
    else:  # pragma: no cover
        raise ValueError(f"unknown kind {kind!r}")


def encode(msg, schema):
    """dict -> wire bytes (fields emitted in field-number order)."""
    out = bytearray()
    by_name = {name: (num, kind, rep)
               for num, (name, kind, rep) in schema.items()}
    for num in sorted(schema):
        name, kind, repeated = schema[num]
        if name not in msg or msg[name] is None:
            continue
        vals = msg[name] if repeated else [msg[name]]
        if repeated and kind in ("int", "float", "double") and vals:
            # packed encoding (proto3 default for repeated scalars)
            payload = bytearray()
            for v in vals:
                if kind == "int":
                    _enc_varint(int(v), payload)
                elif kind == "float":
                    payload.extend(struct.pack("<f", float(v)))
                else:
                    payload.extend(struct.pack("<d", float(v)))
            _enc_varint((num << 3) | 2, out)
            _enc_varint(len(payload), out)
            out.extend(payload)
            continue
        for v in vals:
            _enc_field(num, kind, v, out)
    return bytes(out)


def decode(buf, schema, pos=0, end=None):
    """wire bytes -> dict (repeated fields become lists; missing = absent).

    Unknown fields are skipped, packed and unpacked repeated scalars both
    accepted — enough to read files produced by the official onnx lib."""
    end = len(buf) if end is None else end
    msg = {}

    def put(name, repeated, value):
        if repeated:
            msg.setdefault(name, []).append(value)
        else:
            msg[name] = value

    while pos < end:
        key, pos = _dec_varint(buf, pos)
        num, wt = key >> 3, key & 7
        field = schema.get(num)
        if wt == 0:
            v, pos = _dec_varint(buf, pos)
            if field:
                name, kind, rep = field
                put(name, rep, v)
        elif wt == 5:
            v = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
            if field:
                name, kind, rep = field
                put(name, rep, v)
        elif wt == 1:
            v = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
            if field:
                name, kind, rep = field
                put(name, rep, v)
        elif wt == 2:
            ln, pos = _dec_varint(buf, pos)
            chunk_end = pos + ln
            if field:
                name, kind, rep = field
                if isinstance(kind, dict):
                    put(name, rep, decode(buf, kind, pos, chunk_end))
                elif kind == "string":
                    put(name, rep, buf[pos:chunk_end].decode("utf-8"))
                elif kind == "bytes":
                    put(name, rep, bytes(buf[pos:chunk_end]))
                elif rep and kind in ("int", "float", "double"):
                    # packed scalars
                    p = pos
                    while p < chunk_end:
                        if kind == "int":
                            v, p = _dec_varint(buf, p)
                        elif kind == "float":
                            v = struct.unpack_from("<f", buf, p)[0]
                            p += 4
                        else:
                            v = struct.unpack_from("<d", buf, p)[0]
                            p += 8
                        put(name, True, v)
            pos = chunk_end
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wt}")
    return msg
