"""Symbol/Gluon -> ONNX export.

Reference: ``python/mxnet/contrib/onnx/mx2onnx/export_model.py`` +
``_op_translations.py`` (SURVEY.md §3.5 contrib onnx row): walk the symbol
graph, translate node-by-node into ONNX ops, params become initializers.
"""
from __future__ import annotations

import ast

import numpy as _np

from ...base import MXNetError
from . import ir

__all__ = ["export_model"]


def _attr(attrs, name, default=None):
    v = attrs.get(name, default)
    if isinstance(v, str):
        try:
            v = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            pass
    return v


def _tup(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, (int, float)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _bool(v):
    return str(v).lower() in ("1", "true")


# -- per-op translators: (node, in_names, out_name, attrs, ctxobj) -> [nodes]
def _conv(n, ins, out, a, ctx):
    kernel = _tup(_attr(a, "kernel"))
    return [ir.make_node(
        "Conv", ins, [out], name=n.name, kernel_shape=list(kernel),
        strides=list(_tup(_attr(a, "stride"), len(kernel))),
        dilations=list(_tup(_attr(a, "dilate"), len(kernel))),
        pads=list(_tup(_attr(a, "pad", 0), len(kernel))) * 2,
        group=int(_attr(a, "num_group", 1)))]


def _fc(n, ins, out, a, ctx):
    nodes = []
    data = ins[0]
    if _bool(_attr(a, "flatten", True)):
        flat = f"{n.name}_flat"
        nodes.append(ir.make_node("Flatten", [data], [flat],
                                  name=flat, axis=1))
        gemm_in = [flat, ins[1]] + (ins[2:3] if len(ins) > 2 else [])
        nodes.append(ir.make_node("Gemm", gemm_in, [out], name=n.name,
                                  alpha=1.0, beta=1.0, transA=0, transB=1))
        return nodes
    # flatten=False keeps leading dims (transformer projections): Gemm
    # requires 2-D A, so emit Transpose(W) + MatMul (+ Add) instead
    wt = f"{n.name}_wT"
    nodes.append(ir.make_node("Transpose", [ins[1]], [wt], name=wt,
                              perm=[1, 0]))
    if len(ins) > 2:
        mm = f"{n.name}_mm"
        nodes.append(ir.make_node("MatMul", [data, wt], [mm], name=mm))
        nodes.append(ir.make_node("Add", [mm, ins[2]], [out], name=n.name))
    else:
        nodes.append(ir.make_node("MatMul", [data, wt], [out], name=n.name))
    return nodes


def _bn(n, ins, out, a, ctx):
    return [ir.make_node(
        "BatchNormalization", ins, [out], name=n.name,
        epsilon=float(_attr(a, "eps", 1e-5)),
        momentum=float(_attr(a, "momentum", 0.9)))]


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softsign": "Softsign", "softrelu": "Softplus"}


def _activation(n, ins, out, a, ctx):
    act = _attr(a, "act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"Activation {act!r} has no ONNX mapping")
    return [ir.make_node(_ACT[act], ins, [out], name=n.name)]


def _pooling(n, ins, out, a, ctx):
    ptype = _attr(a, "pool_type", "max")
    if _bool(_attr(a, "global_pool", False)):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [ir.make_node(op, ins, [out], name=n.name)]
    kernel = _tup(_attr(a, "kernel"))
    op = "MaxPool" if ptype == "max" else "AveragePool"
    kw = dict(kernel_shape=list(kernel),
              strides=list(_tup(_attr(a, "stride"), len(kernel))),
              pads=list(_tup(_attr(a, "pad", 0), len(kernel))) * 2)
    if op == "AveragePool":
        kw["count_include_pad"] = int(
            _bool(_attr(a, "count_include_pad", True)))
    return [ir.make_node(op, ins, [out], name=n.name, **kw)]


def _simple(onnx_op, **extra):
    def conv(n, ins, out, a, ctx):
        kw = {}
        for onnx_name, (mx_name, default, cast) in extra.items():
            v = _attr(a, mx_name, default)
            kw[onnx_name] = cast(v) if v is not None else None
        return [ir.make_node(onnx_op, ins, [out], name=n.name, **kw)]

    return conv


def _reshape(n, ins, out, a, ctx):
    shape = _np.asarray(_tup(_attr(a, "shape"), 0), dtype="int64")
    sname = f"{n.name}_shape"
    ctx.initializers.append(ir.make_tensor(sname, shape))
    return [ir.make_node("Reshape", [ins[0], sname], [out], name=n.name)]


def _dropout(n, ins, out, a, ctx):
    # inference export: dropout is identity
    return [ir.make_node("Identity", ins[:1], [out], name=n.name)]


def _leaky(n, ins, out, a, ctx):
    act = _attr(a, "act_type", "leaky")
    if act == "leaky":
        return [ir.make_node("LeakyRelu", ins, [out], name=n.name,
                             alpha=float(_attr(a, "slope", 0.25)))]
    if act == "elu":
        return [ir.make_node("Elu", ins, [out], name=n.name,
                             alpha=float(_attr(a, "slope", 0.25)))]
    raise MXNetError(f"LeakyReLU act_type {act!r} has no ONNX mapping")


_TRANSLATORS = {
    "Convolution": _conv,
    "FullyConnected": _fc,
    "BatchNorm": _bn,
    "Activation": _activation,
    "Pooling": _pooling,
    "Flatten": _simple("Flatten", axis=("axis", 1, int)),
    "flatten": _simple("Flatten", axis=("axis", 1, int)),
    "relu": _simple("Relu"),
    "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"),
    "softsign": _simple("Softsign"),
    "elemwise_add": _simple("Add"),
    "broadcast_add": _simple("Add"),
    "elemwise_sub": _simple("Sub"),
    "broadcast_sub": _simple("Sub"),
    "elemwise_mul": _simple("Mul"),
    "broadcast_mul": _simple("Mul"),
    "elemwise_div": _simple("Div"),
    "broadcast_div": _simple("Div"),
    "softmax": _simple("Softmax", axis=("axis", -1, int)),
    "log_softmax": _simple("LogSoftmax", axis=("axis", -1, int)),
    "concat": _simple("Concat", axis=("dim", 1, int)),
    "Concat": _simple("Concat", axis=("dim", 1, int)),
    "transpose": _simple("Transpose", perm=("axes", None, list)),
    "Dropout": _dropout,
    "LeakyReLU": _leaky,
    "Reshape": _reshape,
    "reshape": _reshape,
    "dot": _simple("MatMul"),
}


class _ExportCtx:
    def __init__(self):
        self.initializers = []


def export_model(sym, params=None, input_shape=None, input_dtype="float32",
                 onnx_file_path="model.onnx", example_input=None):
    """Export a Symbol (+ params dict) or a HybridBlock to an ONNX file.

    Returns the file path (reference: onnx_mxnet.export_model)."""
    from ...symbol.symbol import Symbol, _topo

    arg_params = dict(params or {})
    if not isinstance(sym, Symbol):  # HybridBlock path
        block = sym
        if example_input is None:
            if input_shape is None:
                raise MXNetError("export_model needs input_shape or "
                                 "example_input for a HybridBlock")
            from ... import ndarray as nd

            example_input = nd.zeros(input_shape, dtype=input_dtype)
        sym, args, auxs = block._trace_to_symbol(example_input)
        arg_params = {}
        arg_params.update(args)
        arg_params.update(auxs)
        if isinstance(sym, (list, tuple)):
            sym = sym[0]

    nodes = _topo(sym._heads)
    ctx = _ExportCtx()
    out_name = {}
    graph_nodes = []
    graph_inputs = []

    def tname(node, idx=0):
        if node.op is None:
            return node.name
        return node.name if node.nout == 1 and idx == 0 else \
            f"{node.name}_out{idx}"

    for n in nodes:
        if n.op is None:
            if n.is_const:
                ctx.initializers.append(ir.make_tensor(n.name, n.value))
            elif n.name in arg_params:
                v = arg_params[n.name]
                v = v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v)
                ctx.initializers.append(ir.make_tensor(n.name, v))
            else:
                shape = input_shape if input_shape is not None else ()
                graph_inputs.append(ir.make_value_info(
                    n.name, shape, input_dtype))
            continue
        tr = _TRANSLATORS.get(n.op)
        if tr is None:
            raise MXNetError(
                f"op {n.op!r} has no ONNX translation (node {n.name!r})")
        ins = [tname(inp, idx) for inp, idx in n.inputs]
        graph_nodes.extend(tr(n, ins, tname(n), n.attrs, ctx))

    outputs = [ir.make_value_info(tname(node, idx), (), input_dtype)
               for node, idx in sym._heads]
    graph = {"name": "mxnet_tpu_model", "node": graph_nodes,
             "initializer": ctx.initializers, "input": graph_inputs,
             "output": outputs}
    data = ir.serialize_model(ir.make_model(graph))
    with open(onnx_file_path, "wb") as f:
        f.write(data)
    return onnx_file_path
