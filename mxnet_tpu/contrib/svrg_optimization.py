"""SVRG (stochastic variance-reduced gradient) optimization.

Reference: ``python/mxnet/contrib/svrg_optimization/{svrg_module,
svrg_optimizer}.py`` (SURVEY.md §3.5 contrib misc): SVRGModule keeps a
snapshot of the weights, the full-dataset gradient μ at that snapshot, and
adjusts every minibatch gradient to ``g_i(w) - g_i(w_snap) + μ`` — variance
reduction that restores linear convergence for strongly-convex objectives
(Johnson & Zhang 2013).

TPU-native shape: the reference routes the correction through a special
``_SVRGOptimizer`` registered into the kvstore so parameter-server updates
stay oblivious; here the correction happens at the module level (the
snapshot module's backward runs in the same XLA program family as the main
one, so both gradient evaluations stay on-device) and the base optimizer's
updater is applied to the corrected gradient directly.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient correction.

    Parameters mirror Module plus ``update_freq``: the number of epochs
    between full-gradient snapshots (the reference's semantics).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        if int(update_freq) < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = int(update_freq)
        # snapshot module: same graph, frozen weights w_snap
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._full_grads = None   # μ per param name

    # -- lifecycle mirrors Module, driving both executors ------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        super().init_params(initializer, arg_params, aux_params,
                            allow_missing, force_init, allow_extra)
        self._sync_snapshot()

    def _sync_snapshot(self):
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux)

    def update_full_grads(self, train_data):
        """Snapshot the current weights and accumulate μ = the mean
        gradient of the FULL dataset at those weights (reference:
        SVRGModule.update_full_grads)."""
        import numpy as np

        self._sync_snapshot()
        sums = {}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            nbatch += 1
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                ga = g.asnumpy()
                sums[name] = ga if name not in sums else sums[name] + ga
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty train_data")
        self._full_grads = {k: v / nbatch for k, v in sums.items()}
        train_data.reset()

    def forward_backward(self, data_batch):
        """Main forward/backward plus the snapshot-weight backward on the
        same batch (the two gradient evaluations SVRG needs)."""
        self.forward(data_batch, is_train=True)
        self.backward()
        if self._full_grads is not None:
            self._mod_aux.forward(data_batch, is_train=True)
            self._mod_aux.backward()

    def update(self):
        """Apply the base optimizer to the corrected gradient
        g(w) - g(w_snap) + μ (falls back to plain SGD-style update before
        the first snapshot)."""
        if not self.optimizer_initialized:
            raise MXNetError("call init_optimizer before update")
        from .. import ndarray as nd

        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            if self._full_grads is not None and name in self._full_grads:
                g_snap = self._mod_aux._exec.grad_dict[name]
                grad = grad - g_snap + nd.array(self._full_grads[name])
            self._updater(i, grad, self._exec.arg_dict[name])

    def fit(self, train_data, eval_metric="mse", epoch_end_callback=None,
            batch_end_callback=None, kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=1, **kwargs):
        """SVRG training schedule: refresh μ every ``update_freq`` epochs
        (reference: SVRGModule.fit)."""
        from .. import metric as _metric
        from .. import initializer as _init

        if not self.binded:
            train_data.reset()
            first = next(iter(train_data))
            self.bind(data_shapes=[("data", tuple(first.data[0].shape))],
                      label_shapes=[("softmax_label",
                                     tuple(first.label[0].shape))])
            train_data.reset()
        if not self.params_initialized:
            self.init_params(initializer or _init.Uniform(0.01))
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for batch in train_data:
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback:
                    batch_end_callback(epoch)
            if epoch_end_callback:
                epoch_end_callback(epoch)
        return eval_metric
