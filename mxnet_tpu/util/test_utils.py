"""Test oracles.

Reference: ``python/mxnet/test_utils.py`` (~3k LoC: assert_almost_equal with
per-dtype tolerances, check_numeric_gradient via finite differences,
check_symbolic_forward/backward, check_consistency across contexts,
rand_ndarray, default_context — SURVEY.md §5 oracle list).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import cpu, current_context
from ..ndarray.ndarray import NDArray, array

__all__ = ["default_context", "assert_almost_equal", "almost_equal",
           "rand_ndarray", "rand_shape_nd", "check_numeric_gradient",
           "check_consistency", "same"]

_DTYPE_RTOL = {_np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-4,
               _np.dtype(_np.float64): 1e-6}
_DTYPE_ATOL = {_np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-5,
               _np.dtype(_np.float64): 1e-7}


def default_context():
    return current_context()


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _as_np(a), _as_np(b)
    rtol = rtol or _DTYPE_RTOL.get(a.dtype, 1e-4)
    atol = atol or _DTYPE_ATOL.get(a.dtype, 1e-5)
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np, b_np = _as_np(a), _as_np(b)
    rtol = rtol if rtol is not None else _DTYPE_RTOL.get(a_np.dtype, 1e-4)
    atol = atol if atol is not None else _DTYPE_ATOL.get(a_np.dtype, 1e-5)
    if not _np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=True):
        err = _np.abs(a_np - b_np)
        rel = err / (_np.abs(b_np) + atol)
        raise AssertionError(
            f"{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): max abs err "
            f"{err.max():.3e}, max rel err {rel.max():.3e}\n"
            f"{names[0]}: {a_np.ravel()[:8]}...\n{names[1]}: {b_np.ravel()[:8]}...")


def rand_shape_nd(ndim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, stype="default", density=None, dtype=_np.float32,
                 ctx=None):
    if stype != "default":
        raise NotImplementedError("sparse rand_ndarray arrives with the "
                                  "sparse subsystem")
    return array(_np.random.uniform(-1, 1, size=shape).astype(dtype), ctx=ctx)


def check_numeric_gradient(f, inputs, grads=None, eps=1e-3, rtol=1e-2,
                           atol=1e-3):
    """Finite-difference check of f's gradients computed via autograd.

    f: callable(*NDArrays) -> NDArray (scalar or any shape; summed for grad)
    inputs: list of numpy arrays (float32/float64)
    Reference: check_numeric_gradient (python/mxnet/test_utils.py).
    """
    from .. import autograd

    nds = [array(x.astype(_np.float64).astype(_np.float32)) for x in inputs]
    for nd in nds:
        nd.attach_grad()
    with autograd.record():
        out = f(*nds)
        loss = out.sum()
    loss.backward()
    analytic = [nd.grad.asnumpy() for nd in nds]

    for i, x in enumerate(inputs):
        numeric = _np.zeros_like(x, dtype=_np.float64)
        flat = x.astype(_np.float64).ravel()
        for j in range(flat.size):
            xp = flat.copy()
            xm = flat.copy()
            xp[j] += eps
            xm[j] -= eps
            args_p = [a.copy() for a in inputs]
            args_m = [a.copy() for a in inputs]
            args_p[i] = xp.reshape(x.shape).astype(_np.float32)
            args_m[i] = xm.reshape(x.shape).astype(_np.float32)
            fp = float(f(*[array(a) for a in args_p]).sum().asscalar())
            fm = float(f(*[array(a) for a in args_m]).sum().asscalar())
            numeric.ravel()[j] = (fp - fm) / (2 * eps)
        if not _np.allclose(analytic[i], numeric, rtol=rtol, atol=atol):
            raise AssertionError(
                f"numeric gradient check failed for input {i}:\n"
                f"analytic: {analytic[i].ravel()[:6]}\n"
                f"numeric:  {numeric.ravel()[:6]}")


def check_consistency(f, inputs, ctx_list=None, dtypes=("float32",),
                      rtol=None, atol=None):
    """Run f on the same inputs across contexts/dtypes and compare
    (reference: check_consistency cpu-vs-gpu oracle -> here cpu-vs-tpu /
    fp32-vs-bf16 ladder)."""
    ctx_list = ctx_list or [cpu()]
    ref = None
    for ctx in ctx_list:
        for dt in dtypes:
            nds = [array(x, ctx=ctx, dtype=dt) for x in inputs]
            out = _as_np(f(*nds))
            if ref is None:
                ref = out
            else:
                rt = rtol or (1e-1 if dt == "bfloat16" else 1e-4)
                at = atol or (1e-1 if dt == "bfloat16" else 1e-5)
                if not _np.allclose(ref, out.astype(ref.dtype), rtol=rt, atol=at):
                    raise AssertionError(
                        f"inconsistent results on {ctx}/{dt}: "
                        f"{ref.ravel()[:5]} vs {out.ravel()[:5]}")
    return ref
