"""Utilities (reference: python/mxnet/util.py)."""
from . import test_utils  # noqa: F401


def makedirs(d):
    import os

    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    from ..context import num_gpus

    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    import jax

    try:
        d = jax.devices()[gpu_dev_id]
        stats = d.memory_stats()
        return stats.get("bytes_limit", 0), stats.get("bytes_in_use", 0)
    except Exception:
        return (0, 0)
