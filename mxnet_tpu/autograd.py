"""Imperative autograd: tape recording + backward via per-op ``jax.vjp``.

Reference semantics: ``python/mxnet/autograd.py`` (record/pause/train_mode/
predict_mode scopes, backward, mark_variables) with the C++ tape in
``src/imperative/imperative.cc`` (Imperative::RecordOp builds AGInfo nodes;
Imperative::Backward applies the nnvm "Gradient" pass) — SURVEY.md §3.5, §4.2.

TPU-native design: instead of a graph-IR Gradient pass, every recorded op
captures a *pure function* plus its input values (jax arrays are immutable,
so snapshots are free) and its ``jax.vjp`` residuals at record time.
``backward()`` walks the tape in reverse topological order accumulating
cotangents.  This supports the imperative API (per-op backward, grad_req
write/add, retain_graph) that a whole-function ``jax.grad`` cannot express —
exactly the reason the reference keeps a tape beside its symbolic executor.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as _np

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "Function",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def set_recording(flag):
    prev = _STATE.recording
    _STATE.recording = bool(flag)
    return prev


def set_training(flag):
    prev = _STATE.training
    _STATE.training = bool(flag)
    return prev


@contextmanager
def _scope(recording=None, training=None):
    prev_r, prev_t = _STATE.recording, _STATE.training
    if recording is not None:
        _STATE.recording = recording
    if training is not None:
        _STATE.training = training
    try:
        yield
    finally:
        _STATE.recording, _STATE.training = prev_r, prev_t


def record(train_mode=True):
    """``with autograd.record():`` — turn on tape recording (and train mode)."""
    return _scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _scope(recording=False, training=train_mode)


def train_mode():
    return _scope(training=True)


def predict_mode():
    return _scope(training=False)


# --------------------------------------------------------------------------
# Tape structures
# --------------------------------------------------------------------------
class Entry:
    """A differentiable value on the tape: either an op output (node, oidx)
    or a marked variable (node is None)."""

    __slots__ = ("node", "oidx", "variable", "grad_req", "shape", "dtype")

    def __init__(self, node=None, oidx=0, variable=None, grad_req="write",
                 shape=None, dtype=None):
        self.node = node
        self.oidx = oidx
        self.variable = variable  # the NDArray handle for marked variables
        self.grad_req = grad_req
        self.shape = shape
        self.dtype = dtype


class Node:
    """One recorded op: pure fn + input entries + vjp residuals.

    ``fn``/``in_vals`` are kept so the tape can be *replayed* as a pure jax
    function for ``grad(create_graph=True)`` (vjp-of-vjp — the reference's
    higher-order autograd, tests/python/unittest/test_higher_order_grad.py)."""

    __slots__ = ("vjp_fn", "in_entries", "out_entries", "out_avals", "name",
                 "multi", "fn", "in_vals")

    def __init__(self, vjp_fn, in_entries, out_avals, name="", multi=False,
                 fn=None, in_vals=None):
        self.vjp_fn = vjp_fn
        self.in_entries = in_entries  # list[Entry|None], aligned with vjp cotangent outputs
        self.out_entries = []         # filled by record_op
        self.out_avals = out_avals    # list[(shape, dtype)]
        self.name = name
        self.multi = multi            # original fn returned a tuple
        self.fn = fn                  # pure forward fn (attrs closed over)
        self.in_vals = in_vals        # input snapshot for replay


def record_op(fn, in_vals, in_entries, name=""):
    """Record one op execution. Returns (out_vals, out_entries).

    ``fn`` must be a pure function of ``*in_vals`` (attrs already closed
    over).  Called only when recording AND at least one input is on the tape.
    Reference: Imperative::RecordOp (src/imperative/imperative.cc).
    """
    import jax

    out_vals, vjp_fn = jax.vjp(fn, *in_vals)
    multi = isinstance(out_vals, (tuple, list))
    outs = list(out_vals) if multi else [out_vals]
    node = Node(vjp_fn, list(in_entries),
                [(o.shape, o.dtype) for o in outs], name=name, multi=multi,
                fn=fn, in_vals=list(in_vals))
    node.out_entries = [Entry(node=node, oidx=i, shape=o.shape, dtype=o.dtype)
                        for i, o in enumerate(outs)]
    return out_vals, node.out_entries, multi


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to NDArrays (reference: MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._mark_variable(g, req)


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------
def _topo_nodes(head_entries):
    """Reverse-topological order of nodes reachable from the heads."""
    order, state = [], {}  # state: 0 visiting, 1 done

    def visit(node):
        stack = [(node, False)]
        while stack:
            n, processed = stack.pop()
            if processed:
                state[id(n)] = 1
                order.append(n)
                continue
            st = state.get(id(n))
            if st is not None:
                continue
            state[id(n)] = 0
            stack.append((n, True))
            for e in n.in_entries:
                if e is not None and e.node is not None and state.get(id(e.node)) is None:
                    stack.append((e.node, False))

    for e in head_entries:
        if e is not None and e.node is not None and state.get(id(e.node)) is None:
            visit(e.node)
    order.reverse()
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run backward from ``heads`` (list of NDArray), accumulating into the
    ``.grad`` buffers of marked variables.

    Reference: Imperative::Backward (src/imperative/imperative.cc, SURVEY.md
    §4.2): builds grad graph from tape, executes with inplace-addto.
    Here: reverse-topo walk calling each node's stored ``vjp_fn``.
    """
    import jax.numpy as jnp

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    cot = {}  # id(Entry) -> cotangent jax array
    written = set()  # variables written THIS backward (write-req semantics:
    #                  each backward overwrites; contributions within one
    #                  backward accumulate — matching the reference)

    def add_cot(entry, val):
        k = id(entry)
        if k in cot:
            cot[k] = cot[k] + val
        else:
            cot[k] = val

    head_entries = []
    for h, hg in zip(heads, head_grads):
        e = h._ag_entry
        if e is None:
            raise MXNetError(
                "cannot differentiate a head that was not computed under "
                "autograd.record() from marked variables"
            )
        head_entries.append(e)
        if hg is None:
            g = jnp.ones(h.shape, dtype=h.dtype)
        else:
            g = hg._get() if hasattr(hg, "_get") else jnp.asarray(hg)
        add_cot(e, g)

    for node in _topo_nodes(head_entries):
        outs = []
        have_any = False
        for i, (shape, dtype) in enumerate(node.out_avals):
            e = node.out_entries[i]
            c = cot.pop(id(e), None)
            if c is None:
                c = jnp.zeros(shape, dtype=dtype)
            else:
                have_any = True
            outs.append(c)
        if not have_any:
            continue
        if node.vjp_fn is None:
            raise MXNetError(
                f"backward through node {node.name!r} a second time without "
                "retain_graph=True"
            )
        cotan_in = node.vjp_fn(tuple(outs) if node.multi else outs[0])
        if not retain_graph:
            # free residuals AND the replay snapshot — both pin forward
            # activations in device memory
            node.vjp_fn = None
            node.fn = None
            node.in_vals = None
        for e, c in zip(node.in_entries, cotan_in):
            if e is None or c is None:
                continue
            if e.variable is not None:
                _accum_grad(e, c, written)
            else:
                add_cot(e, c)

    # cotangents that landed directly on variable heads (identity case)
    for e in head_entries:
        if e.variable is not None and id(e) in cot:
            _accum_grad(e, cot.pop(id(e)), written)


def _accum_grad(entry, c, written):
    var = entry.variable
    req = entry.grad_req
    if req == "null" or var is None:
        return
    grad_nd = var._grad
    if grad_nd is None:
        return
    if req == "add":
        grad_nd._set(grad_nd._get() + c)
    elif id(var) in written:  # multiple uses within ONE backward accumulate
        grad_nd._set(grad_nd._get() + c)
    else:  # 'write': first contribution of this backward overwrites
        grad_nd._set(c.astype(grad_nd.dtype) if c.dtype != grad_nd.dtype else c)
        written.add(id(var))


def _replay_fn(head_entries, var_entries, head_vals):
    """Build a pure jax function var_vals -> head_vals by replaying the tape
    (the functional rebuild of the recorded graph that makes the gradient
    itself re-differentiable — reference: the nnvm Gradient pass emits a
    symbolic grad graph that can be differentiated again)."""
    nodes = list(reversed(_topo_nodes(head_entries)))  # forward topo order
    var_ids = [id(e) for e in var_entries]

    def replay(*var_vals):
        val_of = dict(zip(var_ids, var_vals))
        for node in nodes:
            ins = []
            for e, stored in zip(node.in_entries, node.in_vals):
                if e is not None and id(e) in val_of:
                    ins.append(val_of[id(e)])
                else:
                    ins.append(stored)
            if node.fn is None:
                raise MXNetError(
                    f"tape for node {node.name!r} was freed; pass "
                    "retain_graph=True on the earlier backward")
            outs = node.fn(*ins)
            outs_l = list(outs) if node.multi else [outs]
            for oe, ov in zip(node.out_entries, outs_l):
                val_of[id(oe)] = ov
        return tuple(
            val_of.get(id(he), hv) for he, hv in zip(head_entries, head_vals))

    return replay


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional gradient: returns grads of heads w.r.t. variables without
    touching ``.grad`` buffers (reference: mx.autograd.grad).

    ``create_graph=True`` returns gradients that are themselves on the tape,
    enabling grad-of-grad (reference: test_higher_order_grad.py): the tape is
    replayed as a pure jax function and its vjp application is recorded as
    one taped op, so a further backward() differentiates through it
    (vjp-of-vjp).
    """
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
    if create_graph:
        return _grad_create_graph(heads, variables, head_grads)
    from .ndarray import ndarray as _ndm
    saved = [(v._grad, v._ag_entry) for v in variables]
    try:
        zeros = [_ndm.NDArray._from_jax(_zeros_like(v._get()), v.context) for v in variables]
        mark_variables(list(variables), zeros)
        backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
        return [v._grad for v in variables]
    finally:
        for v, (g, e) in zip(variables, saved):
            v._grad, v._ag_entry = g, e


def _grad_create_graph(heads, variables, head_grads=None):
    import jax
    import jax.numpy as jnp

    from .ndarray import ndarray as _ndm

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    head_entries = []
    head_vals = []
    for h in heads:
        if h._ag_entry is None:
            raise MXNetError(
                "cannot differentiate a head that was not computed under "
                "autograd.record() from marked variables")
        head_entries.append(h._ag_entry)
        head_vals.append(h._get())
    var_entries = []
    for v in variables:
        if v._ag_entry is None:
            raise MXNetError(
                f"variable {v!r} is not on the tape (call .attach_grad() "
                "inside or before the record scope)")
        var_entries.append(v._ag_entry)

    replay = _replay_fn(head_entries, var_entries, head_vals)
    hg_vals = [
        jnp.ones(h.shape, dtype=h.dtype) if hg is None
        else (hg._get() if hasattr(hg, "_get") else jnp.asarray(hg))
        for h, hg in zip(heads, head_grads)]

    def grad_fn(*var_vals):
        _, vjp = jax.vjp(replay, *var_vals)
        return vjp(tuple(hg_vals))

    var_vals = [v._get() for v in variables]
    if is_recording():
        out_vals, out_entries, _ = record_op(
            grad_fn, var_vals, var_entries, name="_grad_create_graph")
    else:
        out_vals = grad_fn(*var_vals)
        out_entries = [None] * len(variables)
    results = []
    for v, g, e in zip(variables, out_vals, out_entries):
        nd = _ndm.NDArray._from_jax(g, v.context)
        nd._ag_entry = e
        results.append(nd)
    return results


def _zeros_like(x):
    import jax.numpy as jnp

    return jnp.zeros(x.shape, x.dtype)


# --------------------------------------------------------------------------
# user-defined differentiable functions
# --------------------------------------------------------------------------
def record_callback_node(in_entries, out_nds, backward_cb, name, ctx=None):
    """Attach a tape node to ``out_nds`` whose vjp is a host callback.

    Shared wiring for CustomOp and Function: ``backward_cb`` receives the
    output-gradient NDArrays and returns per-input cotangents
    (NDArray / jax array / None), aligned with ``in_entries``."""
    from .ndarray.ndarray import NDArray

    def vjp_fn(cotangents):
        import jax.numpy as jnp

        cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
        grads = backward_cb([NDArray._from_jax(jnp.asarray(c), ctx)
                             for c in cots])
        return tuple(
            None if g is None else
            (g._get() if hasattr(g, "_get") else jnp.asarray(g))
            for g in grads)

    avals = [(tuple(o.shape), _np.dtype(str(o.dtype))) for o in out_nds]
    node = Node(vjp_fn, list(in_entries), avals, name=name,
                multi=len(out_nds) > 1)
    node.out_entries = [Entry(node=node, oidx=i, shape=s, dtype=d)
                        for i, (s, d) in enumerate(avals)]
    for o, e in zip(out_nds, node.out_entries):
        o._ag_entry = e
    return node


class Function:
    """Customized differentiation (reference: ``mx.autograd.Function``,
    python/mxnet/autograd.py): subclass, implement ``forward`` and
    ``backward`` over NDArrays, stash residuals with ``save_for_backward``
    (or plain attributes on ``self``), call the instance like a function.

    Works eagerly (tape node whose vjp calls the user's ``backward`` —
    full host-Python freedom, matching reference callback semantics) and
    inside ``hybridize()``/jit traces (staged as a ``jax.custom_vjp``; user
    code must then be trace-compatible NDArray math)."""

    def __init__(self):
        self._saved = ()

    # -- user surface ------------------------------------------------------
    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensors(self):
        return self._saved

    # -- invocation --------------------------------------------------------
    def __call__(self, *inputs):
        import jax

        from .ndarray.ndarray import NDArray

        nd_in = [x if isinstance(x, NDArray)
                 else NDArray._from_jax(_as_jax(x), None)
                 for x in inputs]
        in_vals = [a._get() for a in nd_in]
        if any(isinstance(v, jax.core.Tracer) for v in in_vals):
            return self._call_traced(nd_in)
        return self._call_eager(nd_in)

    def _call_eager(self, nd_in):
        from .ndarray.ndarray import NDArray

        ctx = nd_in[0].context if nd_in else None
        with pause():
            out = self.forward(*nd_in)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        if is_recording() and any(a._ag_entry is not None for a in nd_in):
            fname = type(self).__name__

            def backward_cb(out_grad_nds):
                with pause():
                    gin = self.backward(*out_grad_nds)
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                if len(gin) != len(nd_in):
                    raise MXNetError(
                        f"{fname}.backward returned {len(gin)} grads for "
                        f"{len(nd_in)} inputs")
                return gin

            record_callback_node([a._ag_entry for a in nd_in], outs,
                                 backward_cb, f"Function:{fname}", ctx)
        return tuple(outs) if multi else outs[0]

    def _call_traced(self, nd_in):
        import jax

        from .ndarray.ndarray import NDArray

        ctx = nd_in[0].context if nd_in else None
        func = self
        multi_box = []

        @jax.custom_vjp
        def fn(*vals):
            return _fwd(*vals)[0]

        def _fwd(*vals):
            ins = [NDArray._from_jax(v, ctx) for v in vals]
            with pause():
                out = func.forward(*ins)
            multi = isinstance(out, (tuple, list))
            if not multi_box:
                multi_box.append(multi)
            outs = list(out) if multi else [out]
            saved = tuple(t._get() for t in func._saved)
            return tuple(o._get() for o in outs), (vals, saved)

        def _bwd(res, cots):
            import jax.numpy as jnp

            in_vals, saved = res
            func._saved = tuple(NDArray._from_jax(s, ctx) for s in saved)
            grad_nds = [NDArray._from_jax(c, ctx) for c in cots]
            with pause():
                gin = func.backward(*grad_nds)
            gin = gin if isinstance(gin, (tuple, list)) else (gin,)
            return tuple(
                jnp.zeros(v.shape, v.dtype) if g is None else
                (g._get() if hasattr(g, "_get") else jnp.asarray(g))
                for g, v in zip(gin, in_vals))

        fn.defvjp(_fwd, _bwd)
        out_vals = fn(*[a._get() for a in nd_in])
        outs = [NDArray._from_jax(v, ctx) for v in out_vals]
        return tuple(outs) if multi_box and multi_box[0] else outs[0]


def _as_jax(x):
    import jax.numpy as jnp

    return jnp.asarray(x)
