"""Global RNG state: ``mx.random.seed()`` and key distribution.

Reference: ``python/mxnet/random.py`` + per-device RNG resources
(src/resource.cc kRandom/kParallelRandom, SURVEY.md §3.1).  JAX RNG is
explicit-key; the imperative frontend keeps a global key that every random op
splits from — reproducing the reference's "global seed, stateful draw"
semantics — while traced/hybridized code pulls keys from a trace-scoped base
key (threaded in as a jit argument so each call gets fresh randomness).
"""
from __future__ import annotations

import threading

__all__ = ["seed", "get_state", "set_state"]


class _RngState(threading.local):
    def __init__(self):
        self.key = None
        self.trace_stack = []  # [(base_key, counter_box)] during jit tracing


_S = _RngState()


def _jr():
    from jax import random as jr

    return jr


def seed(seed_state, ctx="all"):
    """Seed the global RNG (reference: mx.random.seed)."""
    _S.key = _jr().PRNGKey(int(seed_state))


def get_state():
    """JSON-able global-RNG state (the PRNG key words as a list of ints;
    None = never seeded).  Thread-local: capture on the training thread.
    With :func:`set_state` this makes the stateful-draw sequence resume
    bit-identically across a checkpoint/restore boundary
    (lifecycle.capture_train_state)."""
    if _S.key is None:
        return None
    import numpy as np

    return [int(w) for w in np.asarray(_S.key).ravel()]


def set_state(state):
    """Restore a :func:`get_state` snapshot (None clears back to the
    unseeded default)."""
    if state is None:
        _S.key = None
        return
    import numpy as np
    import jax.numpy as jnp

    _S.key = jnp.asarray(np.asarray(state, dtype=np.uint32))


def _next_key():
    """Next PRNG key: split the global key (eager) or fold a counter into the
    trace-scoped base key (inside hybridize/jit tracing)."""
    jr = _jr()
    if _S.trace_stack:
        base, box = _S.trace_stack[-1]
        box[0] += 1
        return jr.fold_in(base, box[0])
    if _S.key is None:
        _S.key = jr.PRNGKey(0)
    _S.key, sub = jr.split(_S.key)
    return sub


def _in_trace():
    """True while a hybridize/jit trace owns the RNG (keys fold from a
    traced base key).  The eager dispatch cache bypasses needs_rng ops in
    this window — the outer jit owns compilation."""
    return bool(_S.trace_stack)


def _push_trace_key(base_key):
    box = [0]
    _S.trace_stack.append((base_key, box))
    return box


def _pop_trace_key():
    _S.trace_stack.pop()


def uniform(low=0, high=1, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray import ndarray as _nd

    return _nd.invoke("random_uniform", [], {"low": low, "high": high,
                                             "shape": shape or (1,), "dtype": dtype},
                      out=out, ctx=ctx)


def normal(loc=0, scale=1, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray import ndarray as _nd

    return _nd.invoke("random_normal", [], {"loc": loc, "scale": scale,
                                            "shape": shape or (1,), "dtype": dtype},
                      out=out, ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    from .ndarray import ndarray as _nd

    return _nd.invoke("random_randint", [], {"low": low, "high": high,
                                             "shape": shape or (1,), "dtype": dtype},
                      out=out, ctx=ctx)
