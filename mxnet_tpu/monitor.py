"""Monitor: per-step tensor statistics tap (reference:
python/mxnet/monitor.py — stat_func over outputs/weights, regex-filtered)."""
from __future__ import annotations

import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.norm() / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.queue = []
        self.step = 0
        self.activated = False
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def install(self, block):
        """Attach forward hooks to a Gluon block tree."""

        def hook(blk, inputs, output):
            if not self.activated:
                return
            name = blk.name
            outs = output if isinstance(output, (list, tuple)) else [output]
            for i, o in enumerate(outs):
                key = f"{name}_output{i}"
                if self.re_pattern.match(key) and isinstance(o, NDArray):
                    self.queue.append((self.step, key, self.stat_func(o)))

        block.apply(lambda b: b.register_forward_hook(hook))

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        for n, k, v_nd in self.queue:
            res.append((n, k, str(v_nd.asnumpy())))
        self.queue = []
        self.step += 1
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            print(f"Batch: {n:7d} {k:30s} {v}")
