"""Executor: a bound, compiled symbolic graph (reference:
``python/mxnet/executor.py`` + ``src/executor/graph_executor.cc``).

The reference's GraphExecutor runs nnvm passes at bind time (shape/type
inference, memory planning, op-exec attachment) then pushes topo-ordered
segments onto the dependency engine.  Here bind compiles the WHOLE graph —
forward, and forward+backward for training — into single ``jax.jit``
computations: XLA does the memory planning (≙ PlanMemory), fusion
(≙ pointwise_fusion_pass) and scheduling (≙ engine), and the MXU gets one
large program instead of per-op kernel launches.  Gradient construction is
``jax.vjp`` over the interpreted graph (≙ nnvm "Gradient" pass applying
per-op FGradient).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from .base import MXNetError

__all__ = ["Executor"]


def _jax():
    import jax

    return jax


class _LazyOutputs:
    """Sequence proxy returned by forward(is_train=True): touching it
    materializes outputs via the forward jit; leaving it untouched lets the
    fused fwd+bwd jit (backward) produce them for free."""

    __slots__ = ("_ex",)

    def __init__(self, ex):
        self._ex = ex

    def __getitem__(self, i):
        return self._ex.outputs[i]

    def __len__(self):
        return len(self._ex.outputs)

    def __iter__(self):
        return iter(self._ex.outputs)


class Executor:
    """A symbol bound to argument/aux/grad buffers, compiled on demand."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        from .context import current_context
        from .ndarray import NDArray

        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        # normalize args to an ordered dict name -> NDArray
        if isinstance(args, (list, tuple)):
            if len(args) != len(arg_names):
                raise MXNetError(
                    f"bind: {len(arg_names)} arguments expected, got {len(args)}")
            args = dict(zip(arg_names, args))
        elif args is None:
            args = {}
        missing = [n for n in arg_names if n not in args]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")
        self.arg_dict = OrderedDict((n, args[n]) for n in arg_names)

        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        elif aux_states is None:
            aux_states = {}
        missing = [n for n in aux_names if n not in aux_states]
        if missing:
            raise MXNetError(f"bind: missing auxiliary states {missing}")
        self.aux_dict = OrderedDict((n, aux_states[n]) for n in aux_names)

        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict = OrderedDict(
            (n, (args_grad or {}).get(n)) for n in arg_names)

        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        self._arg_names = arg_names
        self._aux_names = aux_names
        self._outputs = []
        self._vjp_inputs = None     # values captured by the last train forward
        self._fwd_cache = {}        # (shapes, dtypes, training) -> jitted fn
        self._bwd_cache = {}
        self._NDArray = NDArray

    @property
    def outputs(self):
        """Lazy for training forwards: the fused fwd+bwd jit computes them,
        so a plain forward→backward step runs the forward exactly once."""
        if self._outputs is None:
            self._materialize_outputs()
        return self._outputs

    # -- compiled graph functions ------------------------------------------
    def _make_forward(self, training):
        from .symbol.symbol import evaluate

        heads = self._symbol._heads

        def fn(arg_vals, aux_vals, rng):
            feed = dict(arg_vals)
            feed.update(aux_vals)
            outs, state = evaluate(heads, feed, rng_key=rng,
                                   training=training, collect_state=training)
            return outs, state

        return _jax().jit(fn, static_argnums=())

    def _make_fused(self, seed_ones):
        """One jitted computation: forward, state collection, AND gradients —
        the whole training step's compute in a single XLA program (the
        reference gets the same effect from engine bulking of the fwd+bwd
        segments; here XLA also fuses across the boundary)."""
        import jax.numpy as jnp

        from .symbol.symbol import evaluate

        heads = self._symbol._heads
        grad_names = [n for n in self._arg_names
                      if self.grad_req.get(n, "null") != "null"]

        def fused(grad_args, other_args, aux_vals, rng, out_grads):
            def f(ga):
                feed = dict(other_args)
                feed.update(ga)
                feed.update(aux_vals)
                outs, state = evaluate(heads, feed, rng_key=rng,
                                       training=True, collect_state=True)
                return outs, state

            outs, vjp_fn, state = _jax().vjp(f, grad_args, has_aux=True)
            if seed_ones:
                ogs = [jnp.ones(o.shape, o.dtype) for o in outs]
            else:
                ogs = out_grads
            (grads,) = vjp_fn(ogs)
            return outs, state, grads

        return _jax().jit(fused), grad_names

    def _sig(self, training):
        shapes = tuple((n, a.shape, str(a.dtype))
                       for n, a in self.arg_dict.items())
        return (shapes, training)

    # -- public API --------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        from . import random as _rnd
        from .ndarray import NDArray

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown argument {k!r}")
            if isinstance(v, NDArray):
                self.arg_dict[k]._set(v._get().astype(
                    self.arg_dict[k]._get().dtype))
            else:
                import jax.numpy as jnp

                self.arg_dict[k]._set(
                    jnp.asarray(v, dtype=self.arg_dict[k]._get().dtype))

        arg_vals = {n: a._get() for n, a in self.arg_dict.items()}
        aux_vals = {n: a._get() for n, a in self.aux_dict.items()}
        rng = _rnd._next_key()
        if is_train:
            # lazy: the fused fwd+bwd jit (backward()) computes outputs too,
            # so the common forward→backward step runs ONE forward; outputs
            # materialize on demand if read before backward
            self._vjp_inputs = (arg_vals, aux_vals, rng)
            self._outputs = None
            return _LazyOutputs(self)
        key = self._sig(False)
        jitted = self._fwd_cache.get(key)
        if jitted is None:
            jitted = self._make_forward(False)
            self._fwd_cache[key] = jitted
        outs, _ = jitted(arg_vals, aux_vals, rng)
        self._vjp_inputs = None
        self._outputs = [NDArray._from_jax(v, self._ctx) for v in outs]
        return self._outputs

    def _materialize_outputs(self):
        from .ndarray import NDArray

        if self._vjp_inputs is None:
            self._outputs = []
            return
        arg_vals, aux_vals, rng = self._vjp_inputs
        key = self._sig(True)
        jitted = self._fwd_cache.get(key)
        if jitted is None:
            jitted = self._make_forward(True)
            self._fwd_cache[key] = jitted
        outs, state = jitted(arg_vals, aux_vals, rng)
        for name, val in state.items():
            if name in self.aux_dict:
                self.aux_dict[name]._set(val)
        self._outputs = [NDArray._from_jax(v, self._ctx) for v in outs]

    def backward(self, out_grads=None):
        if self._vjp_inputs is None:
            raise MXNetError("backward called before forward(is_train=True)")
        import jax.numpy as jnp

        from .ndarray import NDArray

        seed_ones = out_grads is None
        key = (self._sig(True), seed_ones)
        entry = self._bwd_cache.get(key)
        if entry is None:
            entry = self._make_fused(seed_ones)
            self._bwd_cache[key] = entry
        fused, grad_names = entry
        if not grad_names:
            return

        arg_vals, aux_vals, rng = self._vjp_inputs
        grad_args = {n: arg_vals[n] for n in grad_names}
        other_args = {n: v for n, v in arg_vals.items() if n not in grad_args}

        if seed_ones:
            ogs = []
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ogs = [g._get() if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in out_grads]
        outs, state, grads = fused(grad_args, other_args, aux_vals, rng, ogs)
        if self._outputs is None:
            self._outputs = [NDArray._from_jax(v, self._ctx) for v in outs]
            for name, val in state.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set(val)
        for n, g in grads.items():
            req = self.grad_req.get(n, "null")
            if req == "null":
                continue
            buf = self.grad_dict.get(n)
            if buf is None:
                from .ndarray import zeros

                buf = zeros(g.shape, ctx=self._ctx)
                self.grad_dict[n] = buf
            if req == "add":
                buf._set(buf._get() + g)
            else:
                buf._set(g.astype(buf._get().dtype))

    # -- conveniences (reference executor surface) -------------------------
    @property
    def arg_arrays(self):
        return list(self.arg_dict.values())

    @property
    def grad_arrays(self):
        return [self.grad_dict[n] for n in self._arg_names]

    @property
    def aux_arrays(self):
        return list(self.aux_dict.values())

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name]._set(
                    arr._get().astype(self.arg_dict[name]._get().dtype))
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {name!r}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name]._set(
                    arr._get().astype(self.aux_dict[name]._get().dtype))
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        from .ndarray import zeros

        shapes = {n: a.shape for n, a in self.arg_dict.items()}
        shapes.update(kwargs)
        args = {n: zeros(s, ctx=self._ctx) for n, s in shapes.items()}
        for n, a in self.arg_dict.items():
            if args[n].shape == a.shape:
                args[n]._set(a._get())
        grads = None
        if any(r != "null" for r in self.grad_req.values()):
            grads = {n: zeros(s, ctx=self._ctx) for n, s in shapes.items()}
        return Executor(self._symbol, self._ctx, args=args, args_grad=grads,
                        grad_req=self.grad_req, aux_states=dict(self.aux_dict))

    @property
    def output_dict(self):
        return OrderedDict(zip(self._symbol.list_outputs(), self.outputs))
