"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split a batch into num_slice along batch_axis (reference:
    gluon.utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data size {size} not divisible by {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data[begin:end] if batch_axis == 0 else
                      data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice to one context."""
    if not isinstance(data, NDArray):
        from ..ndarray.ndarray import array

        data = array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the total L2 norm <= max_norm (reference:
    gluon.utils.clip_global_norm)."""
    import math

    import jax.numpy as jnp

    total = 0.0
    for a in arrays:
        v = a._get()
        total = total + jnp.sum(jnp.square(v.astype(jnp.float32)))
    total_norm = float(jnp.sqrt(total))
    if check_isfinite and not math.isfinite(total_norm):
        raise MXNetError(f"global norm is not finite ({total_norm})")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set(a._get() * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("download() requires network access, which this "
                     "environment does not have; place files locally instead")
