"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (~1k LoC: Parameter with
deferred shape init via ``_finish_deferred_init``, per-ctx data copies, grad
arrays, grad_req, row_sparse support; ParameterDict with prefix scoping —
SURVEY.md §3.5 "Gluon core").

TPU-native: one NDArray per context (jax places buffers); sharded training
replaces per-ctx copies with a NamedSharding (parallel/), threaded through
``Trainer``.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import ndarray as _ndm
from ..ndarray.ndarray import NDArray
from .. import initializer as init_mod
from .. import autograd

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its deferred shape inference completed."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._data = None      # dict ctx -> NDArray
        self._grad = None      # dict ctx -> NDArray
        self._deferred_init = ()
        self._ctx_list = None
        self._stype = stype

    # -- shape with deferred (0/None) dims --------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape) if new_shape is not None else None
            return
        if new_shape is None:
            return
        if len(self._shape) != len(new_shape) or any(
                s not in (0, n) for s, n in zip(self._shape, new_shape)):
            raise MXNetError(
                f"Parameter {self.name}: incompatible shape {new_shape} vs "
                f"{self._shape}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
        elif self._data is not None:
            self._init_grad()

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not self._shape_known():
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name} because it has "
                f"invalid shape {self._shape} (set allow_deferred_init or "
                "give a full shape)")
        self._finish_deferred_init(init, ctx, default_init)

    def _finish_deferred_init(self, initializer=None, ctx=None, default_init=None):
        """Reference: Parameter._finish_deferred_init — runs at first forward
        once input shapes pin the deferred dims."""
        if self._deferred_init:
            initializer, ctx, default_init = self._deferred_init
            self._deferred_init = ()
        if not self._shape_known():
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}")
        with autograd.pause():
            data = _ndm.invoke("zeros", [], {"shape": self._shape,
                                             "dtype": _np.dtype(self.dtype).name
                                             if self.dtype != "bfloat16" else "bfloat16"},
                               ctx=ctx[0])
            actual_init = initializer or self.init or default_init
            if isinstance(actual_init, str):
                actual_init = init_mod.create(actual_init)
            desc = init_mod.InitDesc(self.name)
            actual_init(desc, data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._data = OrderedDict()
        for c in ctx_list:
            self._data[c] = data if c == ctx_list[0] else data.copyto(c)
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = OrderedDict()
        for c, d in self._data.items():
            g = _ndm.invoke("zeros_like", [d], {})
            self._grad[c] = g
            d._mark_variable(g, self._grad_req)

    # -- access ------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet "
                    "(deferred init pending first forward)")
            raise MXNetError(
                f"Parameter {self.name} has not been initialized. Call "
                ".initialize() first")

    def data(self, ctx=None):
        # under a jit/functionalize trace, hand back the traced stand-in so
        # plain Blocks (not just HybridBlocks) read the traced value instead
        # of baking the concrete buffer in as a constant
        from .block import _TRACE

        tc = _TRACE.ctx
        if tc is not None and self in tc.param_map:
            return tc.param_map[self]
        self._check_initialized()
        if ctx is None:
            return next(iter(self._data.values()))
        if ctx not in self._data:
            raise MXNetError(f"Parameter {self.name} not initialized on {ctx}; "
                             f"available: {list(self._data)}")
        return self._data[ctx]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"Parameter {self.name} has grad_req='null'")
        if ctx is None:
            return next(iter(self._grad.values()))
        return self._grad[ctx]

    def list_grad(self):
        self._check_initialized()
        return list(self._grad.values())

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        self._check_initialized()
        return list(self._data.keys())

    def zero_grad(self):
        if self._grad is None:
            return
        for c, g in self._grad.items():
            g._set(_ndm.invoke("zeros_like", [g], {})._get())

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init:
                self._finish_deferred_init()
            else:
                raise MXNetError(f"Parameter {self.name} not initialized")
        for c, d in self._data.items():
            src = data if isinstance(data, NDArray) else _ndm.array(data)
            d._set(src.as_in_context(c)._get().astype(d._get().dtype))

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = next(iter(self._data.values()))
            self._init_impl(data.copy(), ctx)
        self._ctx_list = list(ctx)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            for c in list(self._data):
                self._data[c] = self._data[c].astype(dtype)
            if self._grad is not None:
                self._init_grad()

    def var(self):
        from ..symbol.symbol import var

        return var(self.name, shape=self.shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-trainable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _ndm.array(value)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(s, _, arr):
                arr._set(value._get())

            def _init_default(s, _, arr):
                arr._set(value._get())

            def __call__(s, desc, arr):
                arr._set(value._get().astype(arr._get().dtype))

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(), differentiable=False)


class ParameterDict:
    """Prefix-scoped dict of Parameters (reference: gluon.ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs):
        """Get-or-create (reference semantics: shared lookup first)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape":
                    param.shape = v
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant named {name} and no value given")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        default = init or init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray.serialization import save as _save

        arg = {}
        for name, param in self.items():
            weight = param.data()
            if not name.startswith(strip_prefix):
                raise MXNetError(f"prefix {strip_prefix} not in {name}")
            arg[name[len(strip_prefix):]] = weight
        _save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray.serialization import load as _load

        loaded = _load(filename)
        loaded = {restore_prefix + k.replace("arg:", "").replace("aux:", ""): v
                  for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise MXNetError(f"Parameter {name} missing in {filename}")
        for name, v in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(f"Parameter {name} in file is not in this "
                                     "ParameterDict (set ignore_extra=True)")
                continue
            p = self._params[name]
            if p._data is None and not p._deferred_init:
                p.shape = v.shape
                p.initialize(ctx=ctx or [current_context()])
            p.set_data(v)

    def __repr__(self):
        s = "\n".join(repr(v) for v in self.values())
        return f"ParameterDict(prefix={self._prefix!r})\n{s}"
