"""Gluon Block / HybridBlock: define-by-run layers with jit staging.

Reference: ``python/mxnet/gluon/block.py`` (~1.5k LoC — Block child/param
registration, forward hooks, save/load_parameters; HybridBlock._build_cache
traces ``hybrid_forward`` with Symbol proxies into a CachedOp; SymbolBlock —
SURVEY.md §3.5, §4.6).

TPU-native staging: ``hybridize()`` swaps the Symbol trace for a ``jax.jit``
trace (SURVEY.md §4.6 calls this "the exact seam where the TPU build swaps in
jax.jit").  The cached computation is a pure function

    fn(param_values, rng_key, *input_values) -> (outputs..., state_updates...)

jit-compiled per (input avals, training-mode, param dtypes).  Parameters ride
as arguments (not constants) so the same executable serves every step;
running-state mutations (BatchNorm moving stats) are threaded out as extra
outputs and written back to their Parameters after the call — the functional
equivalent of the reference's stateful FCompute.  Under ``autograd.record``
the whole cached op lands on the tape as ONE node whose vjp is jax's vjp of
the jitted function (≙ CachedOp backward caching).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from contextlib import contextmanager

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .. import autograd as _ag
from .. import ndarray as _F
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope(threading.local):
    def __init__(self):
        self.counters = {}
        self.scope_stack = []  # active name_scope() (prefix, counters) pairs

    def next_name(self, hint):
        if self.scope_stack:
            # inside `with block.name_scope()`: numbering is per-block
            # (reference: each Block owns a _BlockScope), so two instances
            # of the same model class produce identical child names and
            # save/load round-trips match
            prefix, counters = self.scope_stack[-1]
        else:
            prefix, counters = "", self.counters
        n = counters.get(hint, 0)
        counters[hint] = n + 1
        return f"{prefix}{hint}{n}_"


_NAME_SCOPE = _BlockScope()


class _TraceState(threading.local):
    def __init__(self):
        self.ctx = None  # active _TraceContext or None


_TRACE = _TraceState()


class _TraceContext:
    """Active while hybrid_forward is being traced under jax.jit."""

    def __init__(self, param_map):
        self.param_map = param_map          # Parameter -> traced NDArray
        self.state_updates = []             # [(Parameter, jax value)]


class Block:
    """Base container (reference: gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        if prefix is not None:
            # an explicit prefix is relative to the enclosing name_scope
            # (reference: BlockScope.create prepends the current scope)
            scope = _NAME_SCOPE.scope_stack[-1][0] if \
                _NAME_SCOPE.scope_stack else ""
            self._prefix = scope + prefix
        else:
            self._prefix = _NAME_SCOPE.next_name(self._alias())
        self._params = ParameterDict(self._prefix, shared=params)
        self._children = OrderedDict()
        self._reg_params = OrderedDict()
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    @property
    def params(self):
        return self._params

    @contextmanager
    def name_scope(self):
        """Names of blocks/params created inside are prefixed with this
        block's prefix (reference: Block.name_scope — the idiom every Gluon
        model definition uses).  Numbering restarts per block instance."""
        if not hasattr(self, "_scope_counters"):
            self._scope_counters = {}
        _NAME_SCOPE.scope_stack.append((self._prefix, self._scope_counters))
        try:
            yield
        finally:
            _NAME_SCOPE.scope_stack.pop()

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, "_children", None)
            if existing is not None:
                self._children[name] = value
        elif isinstance(value, Parameter):
            if getattr(self, "_reg_params", None) is not None:
                self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block
        return block

    def register_forward_hook(self, hook):
        key = len(self._forward_hooks)
        self._forward_hooks[key] = hook
        return _HookHandle(self._forward_hooks, key)

    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookHandle(self._forward_pre_hooks, key)

    def collect_params(self, select=None):
        """All Parameters of self + descendants (reference semantics)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, p in self._params.items():
            p.cast(dtype)
        self._bump_cache_version()

    def _bump_cache_version(self):
        pass

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def save_parameters(self, filename, deduplicate=False):
        """Reference: Block.save_parameters — params only, by block-path name."""
        params = self._collect_params_with_prefix()
        from ..ndarray.serialization import save as _save

        _save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        from ..ndarray.serialization import load as _load

        loaded = _load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(f"Parameter {name} missing in {filename}")
        for name, v in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(f"Parameter {name} in file not in Block "
                                     "(set ignore_extra=True)")
                continue
            p = params[name]
            if p._data is None:
                p.shape = v.shape
                p.initialize(ctx=ctx or [current_context()])
            p.set_data(v)
        self._bump_cache_version()

    # legacy aliases
    save_params = save_parameters
    load_params = load_parameters

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference: Block.summary)."""
        rows = []

        def make_hook(name, block):
            def hook(blk, inp, out):
                shape = out.shape if hasattr(out, "shape") else \
                    [o.shape for o in out] if isinstance(out, (list, tuple)) else "?"
                n_params = sum(int(_np.prod(p.shape)) for p in
                               blk._reg_params.values() if p._shape_known())
                rows.append((name or "self", type(blk).__name__, shape, n_params))
            return hook

        handles = []
        for name, child in self._children.items():
            handles.append(child.register_forward_hook(make_hook(name, child)))
        try:
            self(*inputs)
        finally:
            for h in handles:
                h.detach()
        header = f"{'Layer':<24}{'Type':<20}{'Output shape':<24}{'Params':<12}"
        print(header)
        print("-" * len(header))
        total = 0
        for name, typ, shape, n in rows:
            print(f"{name:<24}{typ:<20}{str(shape):<24}{n:<12}")
            total += n
        print("-" * len(header))
        print(f"Total params (shown layers): {total}")

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for key, child in self._children.items():
            s += f"  ({key}): {repr(child)}\n"
        return s + ")"


class _HookHandle:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def detach(self):
        self._hooks.pop(self._key, None)


class HybridBlock(Block):
    """Block that can be staged into a jit-compiled cached op.

    Subclasses implement ``hybrid_forward(F, x, *args, **params)`` — same
    contract as the reference (F is the op namespace; registered params are
    passed as kwargs).
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_graph = {}
        self._cache_version = 0

    def _bump_cache_version(self):
        self._cache_version += 1
        self._cached_graph = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None, backward_bulk_size=None):
        """Reference: HybridBlock.hybridize (flags map to CachedOp config;
        here jit owns memory planning so the flags are accepted no-ops)."""
        self._active = active
        self._flags = {"static_alloc": static_alloc, "static_shape": static_shape}
        self._cached_graph = {}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape)

    def cast(self, dtype):
        self._cached_graph = {}
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred param shapes from input shapes.  Parametric leaf
        layers override this; containers resolve compositionally."""
        raise MXNetError(
            f"{type(self).__name__} has deferred-init parameters but does not "
            "implement infer_shape; give explicit in_units/in_channels or "
            "run one eager forward first")

    # -- eager path --------------------------------------------------------
    def _resolve_params(self, *args):
        kwargs = {}
        tc = _TRACE.ctx
        for name, p in self._reg_params.items():
            if tc is not None and p in tc.param_map:
                kwargs[name] = tc.param_map[p]
                continue
            try:
                kwargs[name] = p.data()
            except DeferredInitializationError:
                self.infer_shape(*args)
                p._finish_deferred_init()
                kwargs[name] = p.data()
        return kwargs

    def _update_running_state(self, param, new_value_nd):
        """Write a non-differentiable state update (BatchNorm moving stats).
        Traced: collected as an extra jit output; eager: written in place."""
        tc = _TRACE.ctx
        val = new_value_nd._get() if isinstance(new_value_nd, NDArray) else new_value_nd
        if tc is not None:
            tc.state_updates.append((param, val))
        else:
            with _ag.pause():
                param.data()._set(val)

    def forward(self, x, *args):
        if self._active and isinstance(x, NDArray) and _TRACE.ctx is None:
            return self._call_cached_op(x, *args)
        params = self._resolve_params(x, *args)
        return self.hybrid_forward(_F, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- cached (jit) path -------------------------------------------------
    def _call_cached_op(self, *args):
        """Reference: _call_cached_op -> CachedOp::Forward (SURVEY.md §4.2).
        Here the cached op is a jax.jit'd pure function."""
        import jax

        # remembered for export() so the symbol trace can re-run shape-true
        # (avals only — holding the arrays would pin the last batch on device)
        self._last_input_shapes = tuple(
            jax.ShapeDtypeStruct(tuple(a.shape), _np.dtype(a.dtype))
            for a in args if isinstance(a, NDArray))

        # deferred param shapes unresolved -> run the eager path once (it
        # settles them, recording normally); the next call builds the cache
        all_params = [p for _, p in sorted(self.collect_params().items())]
        if any(p._data is None for p in all_params):
            params = self._resolve_params(*args)
            return self.hybrid_forward(_F, *args, **params)

        in_vals = [a._get() if isinstance(a, NDArray) else a for a in args]
        from ..ndarray.ndarray import _AMP

        key = (tuple((tuple(v.shape), str(v.dtype)) for v in in_vals),
               _ag.is_training(), _ag.is_recording(), self._cache_version,
               _AMP["target"] if _AMP["on"] else None)
        entry = self._cached_graph.get(key)
        if entry is None:
            entry = self._build_cache(key, all_params, args)
        jitted, params_list, n_state = entry

        param_vals = [p.data()._get() for p in params_list]
        from .. import random as _rnd
        from jax import random as _jr

        rng_key = _rnd._next_key()

        flat_in = param_vals + in_vals
        if _ag.is_recording():
            def fn_for_tape(*flat):
                pv = list(flat[:len(param_vals)])
                iv = list(flat[len(param_vals):])
                return jitted(pv, rng_key, *iv)

            entries = [p.data()._ag_entry for p in params_list] + \
                      [(a._ag_entry if isinstance(a, NDArray) else None) for a in args]
            out_vals, out_entries, _ = _ag.record_op(fn_for_tape, flat_in, entries,
                                                     name=f"cached_op:{self.name}")
        else:
            out_vals = jitted(param_vals, rng_key, *in_vals)
            out_entries = None

        out_vals = list(out_vals)
        state_vals = out_vals[len(out_vals) - n_state:] if n_state else []
        real_vals = out_vals[:len(out_vals) - n_state] if n_state else out_vals

        # write state updates back (BatchNorm stats etc.)
        state_params = self._cached_state_params.get(key, [])
        with _ag.pause():
            for p, v in zip(state_params, state_vals):
                p.data()._set(v)

        ctx = args[0].context if isinstance(args[0], NDArray) else current_context()
        outs = []
        for i, v in enumerate(real_vals):
            o = NDArray._from_jax(v, ctx)
            if out_entries is not None:
                o._ag_entry = out_entries[i]
            outs.append(o)
        if self._cached_single.get(key, len(outs) == 1):
            return outs[0]
        return tuple(outs)

    def _build_cache(self, key, all_params, args):
        """Trace hybrid_forward once into a jit executable (reference:
        _build_cache / CachedOp construction, SURVEY.md §4.6)."""
        import time as _time

        import jax

        # telemetry compile tracer: a fresh build on a block that already
        # has cached entries is a retrace (new input signature / train
        # mode / AMP target) — the thing a retrace storm is made of
        _compile_t0 = _time.perf_counter()
        _compile_cause = "new_block" if not self._cached_graph \
            else "new_signature"
        params_list = all_params
        training = _ag.is_training()
        if not hasattr(self, "_cached_state_params"):
            self._cached_state_params = {}
            self._cached_single = {}

        state_params_box = []
        single_box = []
        block = self

        def fn(param_vals, rng_key, *input_vals):
            from .. import random as _rnd

            pmap = {}
            for p, v in zip(params_list, param_vals):
                nd = NDArray._from_jax(v, None)
                pmap[p] = nd
            tc = _TraceContext(pmap)
            prev = _TRACE.ctx
            _TRACE.ctx = tc
            _rnd._push_trace_key(rng_key)
            prev_rec = _ag.set_recording(False)
            try:
                nd_args = [NDArray._from_jax(v, None) for v in input_vals]
                out = block.forward(*nd_args)
            finally:
                _ag.set_recording(prev_rec)
                _rnd._pop_trace_key()
                _TRACE.ctx = prev
            if isinstance(out, NDArray):
                outs = [out._get()]
                single = True
            else:
                outs = [o._get() for o in out]
                single = False
            state_params = [p for p, _ in tc.state_updates]
            state_vals = [v for _, v in tc.state_updates]
            if not state_params_box:
                state_params_box.append(state_params)
                single_box.append(single)
            return tuple(outs + state_vals)

        jitted = jax.jit(fn, static_argnums=())
        # run an abstract trace to discover state updates & output arity
        in_vals = [a._get() if isinstance(a, NDArray) else a for a in args]
        param_vals = [p.data()._get() for p in params_list]
        from jax import random as _jr

        ref_avals = jax.eval_shape(fn, param_vals, _jr.PRNGKey(0), *in_vals)
        state_params = state_params_box[0]
        n_state = len(state_params)
        self._cached_state_params[key] = state_params
        self._cached_single[key] = single_box[0]

        # graph-compiler tier (ISSUE 11): re-trace forward into the typed
        # graph IR, run the pass pipeline, and jit the optimized replay
        # instead of the raw op-by-op program.  Any trace/validation
        # failure falls back to the imperative jit above — correctness
        # never depends on the optimizer.
        graph_kind = "raw"
        opt_jitted = self._build_graph_entry(
            params_list, args, state_params, single_box[0], ref_avals, key)
        if opt_jitted is not None:
            jitted = opt_jitted
            graph_kind = "optimized"
        entry = (jitted, params_list, n_state)
        self._cached_graph[key] = entry
        from .. import telemetry as _telemetry

        _telemetry.compile_event(
            "block", getattr(self, "name", type(self).__name__) or
            type(self).__name__,
            _time.perf_counter() - _compile_t0, _compile_cause,
            graph=graph_kind)
        return entry

    def _build_graph_entry(self, params_list, args, state_params, single,
                           ref_avals, key):
        """Trace -> optimize -> validate -> jit.  Returns the jitted
        optimized executor, or None (with a ``graph:fallback`` compile
        event) when this forward cannot ride the graph tier."""
        import time as _time

        import jax
        import numpy as _np2

        from .. import graph as _graph
        from .. import telemetry as _telemetry

        if not _graph.enabled() or \
                not all(isinstance(a, NDArray) for a in args):
            return None
        t0 = _time.perf_counter()
        try:
            plist = sorted(self.collect_params().items())
            if [p for _, p in plist] != list(params_list):
                raise MXNetError("graph tier: parameter order drifted")
            input_avals = [jax.ShapeDtypeStruct(
                tuple(a.shape), _np2.dtype(a.dtype)) for a in args]
            g = _graph.trace_block(self, plist, input_avals,
                                   train_mode=_ag.is_training())
            # the traced state heads must target the SAME parameters, in
            # the same order, as the imperative trace discovered
            name_of = {id(p): n for n, p in plist}
            if [name_of[id(p)] for p in state_params] != \
                    [n for n, _ in g.state]:
                raise MXNetError("graph tier: state write-back mismatch")
            if g.single != single:
                raise MXNetError("graph tier: output arity mismatch")
            opt = _graph.default_pipeline().run(g)
            gfn = _graph.make_block_fn(opt)
            param_vals = [p.data()._get() for p in params_list]
            in_vals = [a._get() for a in args]
            got = jax.eval_shape(gfn, param_vals, jax.random.PRNGKey(0),
                                 *in_vals)
            ref = ref_avals if isinstance(ref_avals, (tuple, list)) \
                else (ref_avals,)
            if [(tuple(a.shape), str(a.dtype)) for a in got] != \
                    [(tuple(a.shape), str(a.dtype)) for a in ref]:
                raise MXNetError("graph tier: output aval mismatch")
            if not hasattr(self, "_cached_graph_ir"):
                self._cached_graph_ir = {}
            self._cached_graph_ir[key] = opt
            return jax.jit(gfn)
        except Exception as e:
            _graph.record_fallback()
            _telemetry.compile_event(
                "graph", getattr(self, "name", type(self).__name__) or
                type(self).__name__,
                _time.perf_counter() - t0, "fallback",
                reason=repr(e)[:200])
            return None

    def _trace_to_symbol(self, *args):
        """Trace ``forward`` with SymbolTracer proxies → (Symbol, arg_params,
        aux_params).  Reference: _get_graph building the Symbol from
        hybrid_forward (SURVEY.md §4.6); here imperative forward code runs
        unmodified against graph-building proxies."""
        import jax

        from ..ndarray import ndarray as _ndmod
        from ..symbol.symbol import SymbolTracer, _Node, Symbol

        plist = sorted(self._collect_params_with_prefix().items())
        param_map = {}
        tracers = {}
        for name, p in plist:
            d = p.data()
            aval = jax.ShapeDtypeStruct(d.shape, _np.dtype(d.dtype))
            node = _Node(None, name, {})
            param_map[p] = SymbolTracer((node, 0), aval)
            tracers[name] = param_map[p]

        in_tracers = []
        for i, a in enumerate(args):
            name = "data" if len(args) == 1 else f"data{i}"
            aval = jax.ShapeDtypeStruct(tuple(a.shape), _np.dtype(a.dtype))
            in_tracers.append(SymbolTracer((_Node(None, name, {}), 0), aval))

        tc = _TraceContext(param_map)
        prev = _TRACE.ctx
        _TRACE.ctx = tc
        prev_train = _ag.set_training(False)
        prev_rec = _ag.set_recording(False)
        _ndmod._SYMTRACE["on"] = True
        try:
            out = self.forward(*in_tracers)
        finally:
            _ndmod._SYMTRACE["on"] = False
            _ag.set_recording(prev_rec)
            _ag.set_training(prev_train)
            _TRACE.ctx = prev
        outs = out if isinstance(out, (list, tuple)) else [out]
        heads = [o._symhead for o in outs]
        sym = Symbol(heads)
        # classify by graph position (the symbol knows which vars feed
        # state-op aux slots), not by name suffix
        aux_names = set(sym.list_auxiliary_states())
        arg_params, aux_params = {}, {}
        for name, p in plist:
            if name in aux_names:
                aux_params[name] = p.data()
            else:
                arg_params[name] = p.data()
        return sym, arg_params, aux_params

    def export(self, path, epoch=0, *example_inputs, manifest=True):
        """Reference: HybridBlock.export → ``path-symbol.json`` +
        ``path-{epoch:04d}.params`` (deploy format, loadable by
        SymbolBlock.imports / Module.load_checkpoint).

        Also writes ``path-artifact.json`` — the serving manifest
        (input avals, AMP epoch, StableHLO IR per signature) consumed by
        ``mxnet_tpu.serving.load_artifact``, which reconstructs the
        block and AOT-warms every manifest signature so a server pays
        zero fresh traces in steady state (ISSUE 8; the Relay/TVM
        deployment-IR boundary).  ``manifest=False`` skips it (callers
        like ``serving.export_artifact`` that write a multi-signature
        manifest themselves)."""
        example = example_inputs or getattr(self, "_last_input_shapes", None)
        if not example:
            raise MXNetError(
                "export needs an input signature: call hybridize() and run a "
                "forward pass first, or pass example inputs — "
                "net.export(path, epoch, x) (reference raises the same way)")
        sym, arg_params, aux_params = self._trace_to_symbol(*example)
        from ..module.module import save_checkpoint as _save_ckpt

        _save_ckpt(path, epoch, sym, arg_params, aux_params)
        if manifest:
            from ..serving.artifact import write_manifest

            write_manifest(self, path, epoch=epoch, signatures=[example])


class SymbolBlock(HybridBlock):
    """Run a Symbol graph as a Gluon block (reference: gluon.SymbolBlock —
    python/mxnet/gluon/block.py:~1100, used to reload ``export``ed models).

    Execution interprets the graph with the registered jax op functions via
    ``ndarray.apply_fn``, so autograd works through it and ``hybridize``
    wraps it in one jit computation."""

    def __init__(self, outputs, inputs, params=None, prefix=None):
        super().__init__(prefix=prefix or "")
        from .. import symbol as _sym

        if isinstance(outputs, (list, tuple)):
            outputs = _sym.Group(outputs)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sym = outputs
        self._input_names = [s.name if hasattr(s, "name") else str(s)
                             for s in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        self._sym_aux_names = list(aux_names)
        self._sym_param_names = [n for n in arg_names
                                 if n not in self._input_names] + aux_names
        for n in self._sym_param_names:
            grad_req = "null" if n in aux_names else "write"
            self.params.get(n, grad_req=grad_req, allow_deferred_init=True)
        if params:
            for n, v in params.items():
                key = n.replace("arg:", "").replace("aux:", "")
                if key in self._sym_param_names:
                    self._set_symbol_param(key, v, None)

    def _set_symbol_param(self, key, value, ctx):
        p = self.params.get(key)
        p.shape = tuple(value.shape)
        p.initialize(ctx=ctx, force_reinit=False)
        p.set_data(value)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as _sym
        from ..ndarray.serialization import load as _load

        sym = _sym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym.var(n) for n in input_names]
        blk = SymbolBlock(sym, inputs)
        if param_file is not None:
            loaded = _load(param_file)
            for k, v in loaded.items():
                key = k.replace("arg:", "").replace("aux:", "")
                if key in blk._sym_param_names:
                    blk._set_symbol_param(key, v, ctx)
        return blk

    def _optimized_heads(self):
        """Graph-tier heads: the loaded symbol run through the pass
        pipeline once per cache version (serving's SymbolBlock path runs
        the optimized graph too).  Pipeline off or unoptimizable -> the
        raw heads."""
        from .. import graph as _graph

        if not _graph.enabled():
            return self._sym._heads
        ent = getattr(self, "_opt_heads_entry", None)
        if ent is not None and ent[0] == self._cache_version:
            return ent[1]
        try:
            sym = _graph.default_pipeline().run_symbol(
                self._sym, input_names=self._input_names)
            heads = sym._heads
        except Exception:
            _graph.record_fallback()
            heads = self._sym._heads
        self._opt_heads_entry = (self._cache_version, heads)
        return heads

    def forward(self, *args):
        from .. import random as _rnd
        from ..ndarray.ndarray import NDArray, apply_fn
        from ..symbol.symbol import evaluate

        heads = self._optimized_heads()
        pvals = []
        for n in self._sym_param_names:
            pvals.append(self.params.get(n).data())
        names = self._input_names + self._sym_param_names
        training = _ag.is_training()
        # during training forwards, thread aux-state updates (BatchNorm
        # moving stats) out of the evaluation and write them back into the
        # aux parameters — the reference's CachedOp mutates aux states
        # in-place (ADVICE r1: without this, fine-tuned SymbolBlocks served
        # stale imported running stats)
        aux_names = self._sym_aux_names
        collect = training and bool(aux_names)
        n_main = {}
        key = NDArray._from_jax(_rnd._next_key(), None)

        def pure(key_val, *vals):
            from jax import lax

            feed = dict(zip(names, vals))
            outs, state = evaluate(heads, feed, rng_key=key_val,
                                   training=training, collect_state=collect)
            res = list(outs)
            n_main["n"] = len(res)
            if collect:
                res += [lax.stop_gradient(state.get(n, feed[n]))
                        for n in aux_names]
            return tuple(res) if len(res) != 1 else res[0]

        out = apply_fn(pure, [key] + list(args) + pvals, name="symbol_block")
        if not collect:
            return out
        outs = out if isinstance(out, (list, tuple)) else [out]
        n = n_main["n"]
        main, aux_new = outs[:n], outs[n:]
        tc = _TRACE.ctx
        for nme, v in zip(aux_names, aux_new):
            p = self.params.get(nme)
            if tc is not None:
                # under a functionalize/jit trace the update rides out as an
                # extra jit output (state threading) — writing to .data()
                # here would only mutate the traced stand-in
                tc.state_updates.append((p, v._get()))
            else:
                with _ag.pause():
                    p.data()._set(v._get())
        return main[0] if n == 1 else list(main)
