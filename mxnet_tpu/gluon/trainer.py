"""Gluon Trainer: parameter ↔ kvstore ↔ optimizer wiring.

Reference: ``python/mxnet/gluon/trainer.py`` (~500 LoC: Trainer.step =
_allreduce_grads + _update, the _init_kvstore decision table for
update_on_kvstore — SURVEY.md §3.5, §4.2).

TPU-native: on a single host the per-param "grad ready → reduce" overlap the
reference gets from engine dependencies comes free from jax async dispatch;
multi-host reduction goes through the ``dist_tpu_sync`` KVStore (one psum per
bucket).  For fully-sharded training use parallel.data_parallel's jit step
instead — Trainer remains the imperative-compatible surface.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import guard as _guard
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .. import telemetry as _telemetry
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_STEPS_TOTAL = _telemetry.counter(
    "mxnet_trainer_steps_total", "Trainer.step calls (telemetry=True)")


class Trainer:
    """``telemetry=True`` attributes each ``step()`` to the telemetry step
    timeline: gradient sync as the ``collectives`` phase, the parameter
    update as ``optimizer`` (see :mod:`mxnet_tpu.telemetry`).  Off by
    default — the hot path gains nothing when disabled."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 telemetry=False):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/dict/list")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._params.append(p)
            self._param2idx[p.name] = i
        self._compression_params = compression_params
        self._contains_sparse = False
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._telemetry = bool(telemetry)
        self._bucketer = None   # fused-allreduce plan cache (lazy)
        self._zero = None       # ZeRO-1 sharded-update engine (lazy)
        self._zero_warned = False
        self._zero_done = set()  # param indices updated by ZeRO this step
        self._zero_pending = []  # (generation, bucket) awaiting _update

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params:
                raise MXNetError("optimizer_params must be None when optimizer "
                                 "is an Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """The update_on_kvstore decision (reference decision table:
        dist + not sparse -> update on kvstore unless told otherwise)."""
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kvstore if isinstance(kvstore, kvs_mod.KVStore) else \
                kvs_mod.create(kvstore)
            self._kvstore = kv
            if update_on_kvstore is None:
                update_on_kvstore = "dist" in kv.type
            self._update_on_kvstore = update_on_kvstore
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            for i, p in enumerate(self._params):
                if p._data is not None:
                    kv.init(i, p.data())
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
                # server-side updater owns the optimizer now
        self._kv_initialized = True

    @property
    def step_count(self):
        """Optimizer updates applied so far (``optimizer.num_update``).
        Persisted through save_states/load_states via the pickled
        optimizer; lifecycle.capture_train_state records it as the
        exact-resume cross-check against the supervisor's step number.
        Under ``update_on_kvstore`` the store's (pickle-copied) optimizer
        is the one that advances — the local one never counts there."""
        if self._update_on_kvstore and self._kvstore is not None and \
                self._kvstore._optimizer is not None:
            return self._kvstore._optimizer.num_update
        return self._optimizer.num_update

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None else \
            self._optimizer.lr_scheduler(self._optimizer.num_update)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)
        if self._update_on_kvstore and self._kvstore is not None and \
                self._kvstore._optimizer is not None:
            self._kvstore._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads then update (reference: Trainer.step, §4.2)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._kvstore is not None and self._kvstore._optimizer is not None:
            self._kvstore._optimizer.rescale_grad = self._scale / batch_size
        if self._telemetry:
            _STEPS_TOTAL.inc()
        with _telemetry.maybe_phase(self._telemetry, "collectives"):
            self._allreduce_grads()
        with _telemetry.maybe_phase(self._telemetry, "optimizer"):
            self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _zero_engine(self):
        """The ZeRO-1 engine when the mode is active for this Trainer
        (``MXNET_ZERO=1``, bucketing on, local optimizer ownership, and
        an optimizer with a flat sharded update), else None."""
        from ..parallel import bucketing as _bucketing
        from ..parallel import zero as _zero

        if not _zero.zero_enabled() or self._update_on_kvstore or \
                self._kvstore is None or \
                _bucketing.bucket_cap_bytes() <= 0:
            return None
        if not _zero.supports(self._optimizer):
            if not self._zero_warned:
                import warnings

                warnings.warn(
                    f"MXNET_ZERO=1 but "
                    f"{type(self._optimizer).__name__} has no flat "
                    f"sharded update; optimizer state stays replicated",
                    stacklevel=2)
                self._zero_warned = True
            return None
        if self._zero is None or self._zero.optimizer is not self._optimizer:
            self._zero = _zero.ZeroBucketEngine(self._optimizer)
            # a replicated checkpoint restored into ZeRO mode keeps its
            # momentum: bucket shards adopt the updater's per-key state
            self._zero.adopt = _zero.updater_adopter(self._updaters[0])
        return self._zero

    def _allreduce_grads(self):
        self._zero_done = set()
        self._zero_pending = []
        if self._kvstore is None:
            return
        from ..parallel import bucketing as _bucketing

        if not self._update_on_kvstore and _bucketing.bucket_cap_bytes() > 0:
            self._allreduce_grads_bucketed()
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if self._update_on_kvstore:
                # push grads; server applies optimizer; pull weights back
                self._kvstore.push(i, param.list_grad())
                self._kvstore.pull(i, param.list_data())
            else:
                self._kvstore.push(i, param.list_grad())
                self._kvstore.pull(i, param.list_grad())

    def _allreduce_grads_bucketed(self):
        """Coalesce dense gradients into size-capped flat buckets: K
        per-parameter push/pull round trips become one per bucket
        (parallel/bucketing.py — ceil(total/cap) fused collectives on the
        dist store, one reduce + compression round-trip per bucket
        locally).  Assignment is deterministic in parameter order, so
        every SPMD process issues identical collectives.  Row-sparse and
        host-promoted keys bypass the buckets and keep the per-key path —
        their payload is touched rows, not a stable flat span."""
        from ..ndarray.ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray
        from ..parallel import bucketing as _bucketing

        active = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            active.append((i, param.list_grad()))
        if not active:
            return
        ndev = len(active[0][1])
        grads_by_idx = dict(active)
        entries, bypass = [], []
        for i, grads in active:
            if (isinstance(grads[0], RowSparseNDArray)
                    or len(grads) != ndev
                    or self._kvstore._is_host_key(i)):
                bypass.append(i)
            else:
                entries.append((i, tuple(grads[0].shape),
                                str(grads[0].dtype)))
        if self._bucketer is None:
            # cap=None: plan_for re-reads the env knob per call and folds
            # it into the plan signature, so a mid-run cap change replans
            self._bucketer = _bucketing.Bucketer()
        plan = self._bucketer.plan_for(entries)
        gen = self._bucketer.generation
        zero = self._zero_engine()
        prev_gen = getattr(self, "_bucket_gen_seen", None)
        if prev_gen != gen:
            # a replan retired the previous generation's bucket keys for
            # good: drop their compression residuals (flat arrays up to a
            # full bucket each) or an oscillating signature leaks them —
            # and harvest the retired generation's ZeRO shards so
            # momentum re-flattens into the new plan instead of aliasing
            # a different bucket composition
            self._bucket_gen_seen = gen
            comp = getattr(self._kvstore, "_compression", None)
            if comp is not None and hasattr(comp, "drop_residuals"):
                comp.drop_residuals(
                    lambda k: isinstance(k, str)
                    and k.startswith("__grad_bucket")
                    and not k.endswith(f"g{gen}"))
            if zero is not None and prev_gen is not None:
                zero.retire(("gen", prev_gen))
        for b in plan.buckets:
            if zero is not None and _bucketing.float_kind(b.dtype):
                # ZeRO-1: reduce-scatter the flat bucket, update only
                # this rank's shard (state permanently sharded 1/dp),
                # all-gather the updated params — replaces the fused
                # allreduce + replicated per-param update below.  The
                # step is DEFERRED to _update so the split public API
                # (allreduce_grads → edit grads → update) keeps its
                # semantics: rescale_grad is the one update(batch_size)
                # sets, and in-place grad edits between the calls feed
                # the reduce-scatter (per local contribution — the
                # cross-contribution sum happens inside the collective)
                self._zero_pending.append((gen, b))
                continue
            if not b.fused:
                # singleton (oversized or lone dtype): per-key round trip,
                # no pack/unpack overhead
                (i,) = b.keys
                self._kvstore.push(i, grads_by_idx[i])
                self._kvstore.pull(i, grads_by_idx[i])
                continue
            # the plan generation is part of the key: compression
            # error-feedback residuals are keyed per kvstore key, and a
            # replanned bucket with different composition must not
            # inherit (or shape-clash with) the old plan's residual
            key = f"__grad_bucket{b.index}g{self._bucketer.generation}"
            flats = []
            for j in range(ndev):
                flat = _bucketing.pack(
                    [grads_by_idx[i][j]._get() for i in b.keys])
                flats.append(NDArray._from_jax(
                    flat, grads_by_idx[b.keys[0]][j].context))
            self._kvstore.push(key, flats)
            self._kvstore.pull(key, flats)
            # the reduced flat must not stay resident in the store: that
            # would duplicate the whole dense-grad footprint in HBM
            self._kvstore._discard_transient(key)
            _bucketing.record_fused(b.nbytes)
            if _guard.checksum_enabled():
                # quarantine evidence: the reduced flat is bit-identical
                # on every rank by construction, so its digest diverging
                # across the merged black boxes is proof of SDC/desync
                _guard.stamp_bucket_checksum(key, flats[0]._get(),
                                             step=self.step_count)
            for j in range(ndev):
                for i, part in zip(b.keys,
                                   _bucketing.unpack(b, flats[j]._get())):
                    g = grads_by_idx[i][j]
                    g._set(part.astype(g._get().dtype))
        for i in bypass:
            _bucketing.record_bypass()
            self._kvstore.push(i, grads_by_idx[i])
            self._kvstore.pull(i, grads_by_idx[i])

    def _zero_step_bucket(self, engine, gen, b, grads_by_idx, ndev):
        """One ZeRO bucket step: pack grads + params flat, hand them to
        the engine (reduce-scatter → sharded update → all-gather inside
        one jit), broadcast the updated flat weight back into every
        device slot.  The optimizer phase for these params happened
        inside the collective pair — ``_update`` skips them."""
        from ..ndarray.ndarray import NDArray
        from ..parallel import bucketing as _bucketing

        flats = [_bucketing.pack([grads_by_idx[i][j]._get()
                                  for i in b.keys])
                 for j in range(ndev)]
        w_flat = _bucketing.pack([self._params[i].list_data()[0]._get()
                                  for i in b.keys])
        new_flat = engine.step_bucket(("gen", gen), b, flats, w_flat)
        if _guard.checksum_enabled():
            # post all-gather the updated flat weight is bit-identical
            # across ranks — same quarantine evidence as the fused path
            _guard.stamp_bucket_checksum(
                f"__zero_bucket{b.index}g{gen}", new_flat,
                step=self.step_count)
        for i, part in zip(b.keys, _bucketing.unpack(b, new_flat)):
            param = self._params[i]
            nd_part = NDArray._from_jax(part)
            for d in param.list_data():
                d._set(nd_part.as_in_context(d.context)._get().astype(
                    d._get().dtype))
            self._zero_done.add(i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore and self._kvstore is not None:
            # weights were already updated server-side during _allreduce_grads
            return
        if self._zero_pending:
            pending, self._zero_pending = self._zero_pending, []
            for gen, b in pending:
                grads_by_idx = {i: self._params[i].list_grad()
                                for i in b.keys}
                ndev = len(grads_by_idx[b.keys[0]])
                self._zero_step_bucket(self._zero, gen, b, grads_by_idx,
                                       ndev)
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if i in self._zero_done:
                # ZeRO already applied this param's update inside the
                # reduce-scatter/all-gather pair (sharded state)
                continue
            for w, g in zip(param.list_data(), param.list_grad()):
                updater(i, g, w)

    # Trainer states-file variants, discriminated by an explicit header
    # like the kvstore's MXKVOPT1 (never by speculative unpickling):
    # plain updater blob, or this magic + pickled {"updater": <blob>,
    # "zero": <per-parameter sharded-state pieces>} when ZeRO-1 holds
    # bucketed params' optimizer state in sharded form.  The zero
    # payload is dp- and plan-agnostic (per-member pieces re-flattened
    # from the bucket shard metadata), so a restore works onto a
    # different dp size, a different bucket cap, or with MXNET_ZERO off.
    _ZERO_MAGIC = b"MXTRZRO1"

    def _states_blob(self):
        """The bytes ``save_states`` writes — exposed so async
        checkpointing can snapshot optimizer state on the step loop's
        thread and hand only the file I/O to a background writer."""
        if self._update_on_kvstore and self._kvstore is not None:
            return self._kvstore._optimizer_states_blob(dump_optimizer=True)
        blob = self._updaters[0].get_states(dump_optimizer=True)
        if self._zero is not None and self._zero.has_state:
            import pickle

            return self._ZERO_MAGIC + pickle.dumps(
                {"updater": blob, "zero": self._zero.state_payload()})
        return blob

    def save_states(self, fname):
        """Reference: Trainer.save_states (optimizer state round-trip)."""
        with open(fname, "wb") as f:
            f.write(self._states_blob())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                data = f.read()
            zero_payload = None
            if data.startswith(self._ZERO_MAGIC):
                import pickle

                obj = pickle.loads(data[len(self._ZERO_MAGIC):])
                data, zero_payload = obj["updater"], obj["zero"]
            self._updaters[0].set_states(data)
            self._optimizer = self._updaters[0].optimizer
            self._zero = None  # rebind to the freshly-loaded optimizer
            if zero_payload is not None:
                engine = self._zero_engine()
                if engine is not None:
                    # shards re-flatten lazily at the first step of each
                    # bucket — valid for ANY dp size / bucket plan
                    engine.load_state_payload(zero_payload)
                else:
                    # ZeRO off (or unsupported) at restore time: fold
                    # the sharded pieces back into the replicated
                    # updater so momentum survives the mode switch
                    from ..parallel import zero as _zero

                    _zero.fold_into_updater(self._updaters[0],
                                            zero_payload)
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
