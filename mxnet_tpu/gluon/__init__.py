"""Gluon: the define-by-run high-level API (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import rnn
from . import data
from . import model_zoo
from . import contrib
from . import utils

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "loss", "rnn", "data", "model_zoo",
           "contrib", "utils"]
