"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py
backed by src/operator/image/ — SURVEY.md §3.4).  Operate on HWC uint8/float
numpy arrays or NDArrays; ToTensor converts to CHW float32 NDArray."""
from __future__ import annotations

import numpy as _np

from ...block import Block, HybridBlock
from ...nn import Sequential
from ....ndarray.ndarray import NDArray, array

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomLighting", "RandomColorJitter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return array(_to_np(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def forward(self, x):
        a = _to_np(x).astype(_np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return array(a)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        mean = self._mean.reshape(-1, 1, 1) if a.ndim == 3 else self._mean
        std = self._std.reshape(-1, 1, 1) if a.ndim == 3 else self._std
        return array((a - mean) / std)


def _resize_np(img, size):
    """Bilinear resize HWC numpy image to (w, h) size."""
    import jax
    import jax.numpy as jnp

    h, w = size[1], size[0]
    out = jax.image.resize(jnp.asarray(img.astype(_np.float32)),
                           (h, w, img.shape[2]), method="bilinear")
    return _np.asarray(out)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._keep = keep_ratio

    def forward(self, x):
        img = _to_np(x)
        w, h = self._size
        if self._keep:
            ih, iw = img.shape[:2]
            scale = min(w / iw, h / ih)
            w, h = int(iw * scale), int(ih * scale)
        return array(_resize_np(img, (w, h)).astype(img.dtype if
                     img.dtype == _np.float32 else _np.uint8))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        img = _to_np(x)
        w, h = self._size
        ih, iw = img.shape[:2]
        x0 = max((iw - w) // 2, 0)
        y0 = max((ih - h) // 2, 0)
        crop = img[y0:y0 + h, x0:x0 + w]
        if crop.shape[:2] != (h, w):
            crop = _resize_np(crop, (w, h)).astype(img.dtype)
        return array(crop)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        img = _to_np(x)
        ih, iw = img.shape[:2]
        area = ih * iw
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            w = int(round(_np.sqrt(target_area * aspect)))
            h = int(round(_np.sqrt(target_area / aspect)))
            if w <= iw and h <= ih:
                x0 = _np.random.randint(0, iw - w + 1)
                y0 = _np.random.randint(0, ih - h + 1)
                crop = img[y0:y0 + h, x0:x0 + w]
                out = _resize_np(crop, self._size)
                return array(out.astype(_np.uint8) if img.dtype == _np.uint8
                             else out)
        # fallback: center crop
        return CenterCrop(self._size).forward(x)


class RandomHorizontalFlip(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        img = _to_np(x)
        if _np.random.rand() < self._p:
            img = img[:, ::-1]
        return array(_np.ascontiguousarray(img))


class RandomVerticalFlip(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        img = _to_np(x)
        if _np.random.rand() < self._p:
            img = img[::-1]
        return array(_np.ascontiguousarray(img))


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + _np.random.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        return array(_np.clip(img * self._factor(), 0, 255))


class RandomContrast(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        gray = img.mean()
        return array(_np.clip((img - gray) * self._factor() + gray, 0, 255))


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        gray = img.mean(axis=-1, keepdims=True)
        return array(_np.clip((img - gray) * self._factor() + gray, 0, 255))


class RandomLighting(Block):
    """AlexNet-style PCA lighting jitter."""

    _eigval = _np.array([55.46, 4.794, 1.148])
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]])

    def __init__(self, alpha=0.1):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        alpha = _np.random.normal(0, self._alpha, 3)
        rgb = (self._eigvec @ (alpha * self._eigval)).astype(_np.float32)
        return array(_np.clip(img + rgb, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        ts = list(self._ts)
        _np.random.shuffle(ts)
        for t in ts:
            x = t.forward(x)
        return x
