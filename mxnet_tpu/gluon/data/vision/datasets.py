"""Vision datasets.

Reference: ``python/mxnet/gluon/data/vision/datasets.py`` (MNIST, FashionMNIST,
CIFAR10/100, ImageRecordDataset, ImageFolderDataset).  This environment has
no network egress, so constructors read standard local files when present and
raise otherwise; ``SyntheticImageDataset`` provides deterministic data for
tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _np

from ....base import MXNetError
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset", "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx-format files (train-images-idx3-ubyte.gz etc.)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._base = "train" if train else "t10k"
        super().__init__(root, train, transform)

    def _get_data(self):
        img = os.path.join(self._root, f"{self._base}-images-idx3-ubyte.gz")
        lbl = os.path.join(self._root, f"{self._base}-labels-idx1-ubyte.gz")
        for p in (img, lbl):
            if not os.path.exists(p):
                raise MXNetError(
                    f"MNIST file {p} not found and no network egress is "
                    "available; place the files locally or use "
                    "SyntheticImageDataset for testing")
        with gzip.open(lbl, "rb") as f:
            struct.unpack(">II", f.read(8))
            label = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)
        with gzip.open(img, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = _np.frombuffer(f.read(), dtype=_np.uint8)
            data = data.reshape(n, rows, cols, 1)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        data, labels = [], []
        for b in batches:
            p = os.path.join(self._root, "cifar-10-batches-py", b)
            if not os.path.exists(p):
                raise MXNetError(f"CIFAR-10 file {p} not found (no network "
                                 "egress); place files locally")
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="latin1")
            data.append(d["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.extend(d["labels"])
        self._data = _np.concatenate(data)
        self._label = _np.asarray(labels, dtype=_np.int32)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        name = "train" if self._train else "test"
        p = os.path.join(self._root, "cifar-100-python", name)
        if not os.path.exists(p):
            raise MXNetError(f"CIFAR-100 file {p} not found (no network egress)")
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="latin1")
        self._data = d["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._label = _np.asarray(d[key], dtype=_np.int32)


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (reference:
    vision/datasets.py ImageRecordDataset over .rec)."""

    def __init__(self, filename, flag=1, transform=None):
        from ....recordio import MXIndexedRecordIO, unpack_img

        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform
        self._unpack_img = unpack_img

    def __getitem__(self, idx):
        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._unpack_img(record, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record.keys)


class ImageFolderDataset(Dataset):
    """folder/label_name/*.png layout (reference: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if filename.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread

        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = _np.load(path)
        else:
            img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class SyntheticImageDataset(Dataset):
    """Deterministic synthetic images for tests/benchmarks (no reference
    counterpart; stands in for downloads in this offline environment)."""

    def __init__(self, length=1024, shape=(32, 32, 3), num_classes=10,
                 transform=None, seed=0):
        self._length = length
        self._shape = tuple(shape)
        self._num_classes = num_classes
        self._transform = transform
        rng = _np.random.RandomState(seed)
        self._data = rng.randint(0, 256, (length,) + self._shape,
                                 dtype=_np.uint8)
        self._label = rng.randint(0, num_classes, (length,)).astype(_np.int32)

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return self._length
