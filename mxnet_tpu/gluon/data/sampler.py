"""Samplers (reference: python/mxnet/gluon/data/sampler.py).

Exact-resume contract (lifecycle.capture_train_state): samplers expose
``state_dict()``/``load_state_dict()`` and an optional ``set_epoch(e)``
so a resumed DataLoader can regenerate the SAME index sequence a killed
run was consuming.  ``RandomSampler`` therefore shuffles from its own
seeded RNG — a per-epoch permutation that is a pure function of
``(seed, epoch)`` — instead of the global numpy RNG, whose state at
epoch start is unrecoverable after a preemption.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    # exact-resume hooks: stateless samplers inherit the no-ops
    def state_dict(self):
        return {}

    def load_state_dict(self, state):
        pass


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Shuffled indices, deterministic per ``(seed, epoch)``.

    ``seed`` defaults to a draw from the global numpy RNG (so unseeded
    behavior still varies run to run) but is RECORDED: ``state_dict()``
    carries it, and a resumed sampler replays the exact permutations.
    Each ``__iter__`` consumes one epoch (the counter advances); a
    driver that owns epoch numbering (DataLoader) pins it with
    ``set_epoch`` instead."""

    def __init__(self, length, seed=None):
        self._length = length
        if seed is None:
            seed = int(_np.random.randint(0, 2 ** 31 - 1))
        self._seed = int(seed)
        self._epoch = 0

    def set_epoch(self, epoch):
        """Pin the epoch the next ``__iter__`` permutes for."""
        self._epoch = int(epoch)

    def __iter__(self):
        rs = _np.random.RandomState(
            (self._seed + self._epoch) % (2 ** 32))
        self._epoch += 1
        return iter(rs.permutation(self._length).tolist())

    def __len__(self):
        return self._length

    def state_dict(self):
        return {"seed": self._seed, "epoch": self._epoch}

    def load_state_dict(self, state):
        if not state:   # state from a stateless sampler config: keep ours
            return
        self._seed = int(state.get("seed", self._seed))
        self._epoch = int(state.get("epoch", self._epoch))


class BatchSampler(Sampler):
    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(f"last_batch must be keep/discard/rollover, "
                                 f"got {self._last_batch}")

    def __len__(self):
        n = len(self._sampler)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + len(self._prev)) // self._batch_size

    def set_epoch(self, epoch):
        se = getattr(self._sampler, "set_epoch", None)
        if se is not None:
            se(epoch)

    def state_dict(self):
        # _prev is the rollover carry consumed at the NEXT epoch's start;
        # capturing it keeps last_batch="rollover" exactly resumable
        return {"sampler": self._sampler.state_dict()
                if hasattr(self._sampler, "state_dict") else {},
                "prev": list(self._prev)}

    def load_state_dict(self, state):
        if hasattr(self._sampler, "load_state_dict"):
            self._sampler.load_state_dict(state.get("sampler") or {})
        self._prev = [int(i) for i in state.get("prev") or []]
