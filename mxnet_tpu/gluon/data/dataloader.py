"""DataLoader with background prefetch.

Reference: ``python/mxnet/gluon/data/dataloader.py`` (_MultiWorkerIter with
multiprocessing workers + POSIX-shm zero-copy batches — SURVEY.md §3.4).

TPU-native: worker processes would fight the TPU runtime for the process
space; the idiomatic host-side pipeline is a thread pool (NumPy decode
releases the GIL in the hot paths) feeding a device-prefetch queue —
same shape as the reference's parser→batcher→prefetcher pipeline (§4.5).
``num_workers`` maps to the thread pool size.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    from ...ndarray.ndarray import NDArray, array

    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    arr = _np.asarray(data)
    return array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch or 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        pool = ThreadPoolExecutor(max_workers=self._num_workers)
        batches = list(self._batch_sampler)

        def load(batch):
            return self._batchify_fn([self._dataset[i] for i in batch])

        try:
            futures = queue.Queue()
            it = iter(batches)
            # prime the prefetch window
            primed = 0
            for batch in it:
                futures.put(pool.submit(load, batch))
                primed += 1
                if primed >= self._prefetch:
                    break
            while not futures.empty():
                f = futures.get()
                try:
                    nxt = next(it)
                    futures.put(pool.submit(load, nxt))
                except StopIteration:
                    pass
                yield f.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
