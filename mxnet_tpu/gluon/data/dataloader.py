"""DataLoader with background prefetch.

Reference: ``python/mxnet/gluon/data/dataloader.py`` (_MultiWorkerIter with
multiprocessing workers + POSIX-shm zero-copy batches — SURVEY.md §3.4).

TPU-native: the default host-side pipeline is a thread pool (NumPy decode
releases the GIL in the hot paths) feeding a device-prefetch queue — same
shape as the reference's parser→batcher→prefetcher pipeline (§4.5), and
threads never fight the TPU runtime for the process space.  For GIL-bound
user transforms (pure-Python ``transform_fn``s that never release the
GIL), pass ``thread_pool=False`` to get PROCESS workers — the reference's
multiprocessing design with pickle transport: workers run dataset[i] +
batchify to plain numpy (no device runtime in children) and the parent
converts to NDArray.  ``num_workers`` sizes either pool.
"""
from __future__ import annotations

import queue
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ... import telemetry
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]

_BATCH_WAIT = telemetry.histogram(
    "mxnet_dataloader_batch_wait_seconds",
    "time the consumer waited for the next batch")
_BATCHES_TOTAL = telemetry.counter(
    "mxnet_dataloader_batches_total", "batches yielded")
_WORKERS_GAUGE = telemetry.gauge(
    "mxnet_dataloader_workers",
    "live process-pool workers (of the most recently active loader)")
_WORKER_DEATHS = telemetry.counter(
    "mxnet_dataloader_worker_deaths_total",
    "abnormal process-worker deaths detected mid-epoch")


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    from ...ndarray.ndarray import NDArray, array

    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    arr = _np.asarray(data)
    return array(arr)


def default_mp_batchify_fn(data):
    """Stack samples into a NUMPY batch — the worker-process batchify
    (reference: default_mp_batchify_fn building shared-memory NDArrays).
    Children must not touch the device runtime; the parent converts."""
    if isinstance(data[0], tuple):
        return tuple(default_mp_batchify_fn(list(d)) for d in zip(*data))
    if hasattr(data[0], "asnumpy"):
        return _np.stack([d.asnumpy() for d in data])
    return _np.asarray(data)


_worker_dataset = None
# set in the CHILD when the jax CPU pin failed there: a mis-pinned worker
# can grab the TPU runtime, and the symptom (a wedged axon tunnel or an
# OOM half an epoch later) otherwise never points back to this cause
_worker_pin_error = None


def _worker_initializer(dataset):
    global _worker_dataset, _worker_pin_error
    _worker_dataset = dataset
    # pin any jax use in this child to CPU BEFORE its first dispatch (env
    # alone is not enough where a sitecustomize force-selects the platform
    # via jax config); effective for spawn children and for fork children
    # whose parent has not initialized a device backend yet
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception as e:
        import logging
        import os as _os

        _worker_pin_error = f"{type(e).__name__}: {e}"
        logging.getLogger(__name__).warning(
            "DataLoader worker pid=%d: jax CPU pin failed (%s) — this "
            "child may initialize the device runtime", _os.getpid(),
            _worker_pin_error)


def _terminate_pool(pool, stops=()):
    # unblock any active epoch's gated() generator FIRST: the pool's
    # task-handler thread sits inside it, and terminate() joins that
    # thread — without the stop signal the join deadlocks
    for s in list(stops):
        s.set()
    pool.terminate()
    pool.join()


class _WorkerFn:
    """Picklable per-batch task: dataset[i] for the batch + batchify."""

    def __init__(self, batchify_fn):
        self._fn = batchify_fn

    def __call__(self, batch):
        from ... import fault

        # seam is armed via MXNET_FAULT_SPEC (the env reaches spawn
        # children) — in-process inject() plans do not cross the fork
        fault.check("dataloader.worker")
        try:
            return self._fn([_worker_dataset[i] for i in batch])
        except Exception as e:
            if _worker_pin_error is not None:
                # the pickled traceback loses child-side logs; carry the
                # pin diagnosis inside the exception that crosses back
                raise RuntimeError(
                    f"{type(e).__name__}: {e} [worker jax CPU pin had "
                    f"failed: {_worker_pin_error}]") from e
            raise


def _to_nd(out):
    from ...ndarray.ndarray import array

    if isinstance(out, tuple):
        return tuple(_to_nd(o) for o in out)
    if isinstance(out, _np.ndarray):
        return array(out)
    return out


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, prefetch_to_device=None):
        """``prefetch_to_device``: overlap host→device staging with the
        training step (gluon/data/prefetcher.py).  ``True`` prefetches to
        the default device; a ``jax.sharding.Sharding`` (e.g. a
        TrainStep's ``_batch_shard``) places the global batch.  Depth is
        ``MXNET_PREFETCH_BUFFER`` (default 2; 0 turns the pipeline off
        and batches stage inline on the consumer's thread)."""
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch or 2 * max(num_workers, 1))
        self._prefetch_to_device = prefetch_to_device
        self._proc_pool = None          # persistent process pool (spawn is
        self._proc_pool_method = None   # expensive: pay startup once)
        self._pool_finalizer = None
        self._active_stops = set()      # stop events of live epoch iters
        # exact-resume position (lifecycle.capture_train_state): epoch of
        # the iterator currently live, batches the CONSUMER received from
        # it, the batch-sampler state as of that epoch's start, and a
        # pending resume point applied by the next __iter__
        self._epoch = -1
        self._batches_served = 0
        self._epoch_start_state = None
        self._skip_next = 0
        self._resume = None

    def __len__(self):
        return len(self._batch_sampler)

    def state_dict(self):
        """Resume point for :meth:`load_state_dict`: the live epoch, how
        many batches the consumer already received from it, and the
        batch-sampler state as of the epoch start (shuffle seed + epoch
        + rollover carry).  Capture at a step boundary; state tracking
        assumes ONE active iterator per loader (the training loop's)."""
        if self._resume is not None:
            # captured before the armed resume point was consumed by an
            # __iter__: the position is still the armed one
            return dict(self._resume)
        return {"epoch": max(self._epoch, 0),
                "batch": self._batches_served,
                "sampler": self._epoch_start_state}

    def load_state_dict(self, state):
        """Arm the next ``__iter__`` to resume at ``state``: the sampler
        regenerates the recorded epoch's index sequence and the first
        ``state["batch"]`` batches are skipped DECODE-FREE — only index
        lists are consumed, ``dataset[i]`` is never called for them —
        so fast-forwarding a multi-epoch position costs microseconds,
        not an epoch of decode."""
        self._resume = dict(state or {})

    def _begin_epoch(self):
        """Apply epoch numbering (and any armed resume point) before the
        underlying iterator is built; returns nothing, sets counters."""
        resume, self._resume = self._resume, None
        if resume is not None:
            self._epoch = int(resume.get("epoch") or 0)
            self._skip_next = int(resume.get("batch") or 0)
            sd = resume.get("sampler")
            if sd is not None and hasattr(self._batch_sampler,
                                          "load_state_dict"):
                self._batch_sampler.load_state_dict(sd)
            elif self._skip_next:
                # no captured sampler state, OR state that the rebuilt
                # sampler cannot load: we can fast-forward the COUNT but
                # not replay the order — if the sampler reshuffles,
                # skipped batches come from a DIFFERENT permutation and
                # data is silently repeated or lost.  Exact resume needs
                # state_dict AND load_state_dict (and ideally set_epoch)
                # on the batch sampler.
                import warnings

                warnings.warn(
                    "DataLoader resume: the batch sampler "
                    + ("recorded no state (no state_dict())"
                       if sd is None else
                       "cannot restore its recorded state "
                       "(no load_state_dict())")
                    + f"; skipping {self._skip_next} batches of a "
                    "potentially DIFFERENT order — the resumed sequence "
                    "is only bit-identical for deterministic samplers",
                    stacklevel=3)
        else:
            self._epoch += 1
            self._skip_next = 0
        se = getattr(self._batch_sampler, "set_epoch", None)
        if se is not None:
            se(self._epoch)
        self._epoch_start_state = self._batch_sampler.state_dict() \
            if hasattr(self._batch_sampler, "state_dict") else None
        # skipped batches were already consumed by the killed run
        self._batches_served = self._skip_next

    def _epoch_batches(self):
        """Index-batches of the current epoch, with the resume skip
        applied: the fast-forward drains index lists only — decode-free."""
        it = iter(self._batch_sampler)
        skip, self._skip_next = self._skip_next, 0
        for _ in range(skip):
            if next(it, None) is None:
                return
        yield from it

    def __iter__(self):
        # batch-wait attribution: time from the consumer asking for the
        # next batch to it being ready — with a prefetching pool this is
        # the stall the training loop actually feels, the "data wait"
        # answer to "why was this step slow?"  The device prefetcher sits
        # INSIDE this measurement so the histogram shows the shrink.
        self._begin_epoch()
        it = self._iter_impl()
        pf = None
        if self._prefetch_to_device:
            from .prefetcher import PrefetchIterator

            sharding = self._prefetch_to_device \
                if self._prefetch_to_device is not True else None
            pf = it = PrefetchIterator(it, sharding=sharding)
        try:
            while True:
                t0 = _time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    return
                _BATCH_WAIT.observe(_time.perf_counter() - t0)
                _BATCHES_TOTAL.inc()
                self._batches_served += 1
                yield batch
        finally:
            # runs on exhaustion, break, and generator GC alike — a
            # SIGKILLed worker's error must not strand the prefetch thread
            if pf is not None:
                pf.close()

    def _iter_impl(self):
        if self._num_workers == 0:
            for batch in self._epoch_batches():
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        if self._thread_pool:
            yield from self._threaded_iter()
        else:
            yield from self._process_iter()

    def _process_iter(self):
        """Process workers for GIL-bound transforms (reference:
        _MultiWorkerIter).  Workers produce numpy batches (pickle
        transport); the parent converts to NDArray.

        Start method defaults to ``spawn``: the parent is effectively
        always multi-threaded (prefetch ThreadPoolExecutor, jax runtime
        internals), and fork() from a multi-threaded process can deadlock
        children on inherited locks (Python 3.12 DeprecationWarning) — and
        a forked child would also inherit a live TPU client (the axon
        tunnel is single-client).  ``fork`` remains an explicit opt-in via
        MXNET_MP_START_METHOD=fork (``forkserver`` also accepted).  Spawn
        imposes the standard multiprocessing contract fork did not: the
        dataset/batchify must be picklable (no lambdas) and scripts that
        iterate a DataLoader at module top level need an
        ``if __name__ == "__main__":`` guard.  Either way the worker
        initializer pins jax in the child to CPU before any dispatch.

        The pool PERSISTS across epochs (a spawn startup per __iter__
        would cost num_workers interpreter launches + imports every
        epoch): workers snapshot the dataset once at pool creation, so
        in-place dataset mutations between epochs are not visible to
        process workers — build a new DataLoader for a new dataset."""
        import multiprocessing as mp

        from ...base import MXNetError

        fn = self._batchify_fn
        if fn is default_batchify_fn:
            fn = default_mp_batchify_fn
        pool = self._get_proc_pool()
        # bound in-flight work: imap's feeder thread would otherwise
        # enqueue the whole epoch and buffer every finished batch.  The
        # stop event unblocks the feeder if the consumer abandons the
        # iterator early (queued tasks drain harmlessly in the background
        # of the persistent pool).
        sem = threading.BoundedSemaphore(self._num_workers + self._prefetch)
        stop = threading.Event()
        # registered so close()/pool teardown can unblock gated() even
        # when this generator was abandoned without being closed
        self._active_stops.add(stop)

        def gated():
            for b in self._epoch_batches():
                while not sem.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                yield b

        # liveness snapshot: Pool's maintenance thread silently replaces a
        # dead worker in pool._pool, but the batch the casualty held never
        # completes — a blind `for out in imap(...)` then hangs forever.
        # Holding the ORIGINAL Process objects lets the poll below see the
        # death (exitcode flips non-None; workers never exit on their own
        # while the pool lives, so any exit mid-epoch is abnormal).
        workers = list(pool._pool)
        it = pool.imap(_WorkerFn(fn), gated())
        idx = 0
        try:
            while True:
                try:
                    out = it.next(timeout=0.2)
                except StopIteration:
                    break
                except mp.TimeoutError:
                    dead = [p for p in workers if p.exitcode is not None]
                    _WORKERS_GAUGE.set(len(workers) - len(dead))
                    if dead:
                        _WORKER_DEATHS.inc(len(dead))
                        # the pool's task bookkeeping is now unknowable
                        # (the dead child's in-flight batch is lost);
                        # discard it so the NEXT epoch gets clean workers.
                        # stop MUST be set before teardown: the pool's
                        # task-handler thread is inside gated() and the
                        # teardown joins it
                        stop.set()
                        self._abandon_proc_pool()
                        raise MXNetError(
                            "DataLoader process worker(s) died while "
                            f"computing batch {idx}: "
                            + ", ".join(f"pid={p.pid} exitcode={p.exitcode}"
                                        for p in dead)
                            + " (killed by the OOM killer or a signal?); "
                            "the worker pool was recycled — re-iterate to "
                            "respawn workers")
                    continue
                except MXNetError:
                    raise
                except Exception as e:
                    # worker-side failure pickled back through imap: name
                    # the batch so the bad sample/transform is findable
                    raise MXNetError(
                        f"DataLoader worker failed on batch {idx}: "
                        f"{type(e).__name__}: {e}") from e
                sem.release()
                yield _to_nd(out)
                idx += 1
        finally:
            stop.set()
            self._active_stops.discard(stop)

    def _get_proc_pool(self):
        import multiprocessing as mp
        import os
        import weakref

        method = os.environ.get("MXNET_MP_START_METHOD") or "spawn"
        if self._proc_pool is not None and self._proc_pool_method == method:
            return self._proc_pool
        self._shutdown_proc_pool()
        ctx = mp.get_context(method)
        prev = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            pool = ctx.Pool(self._num_workers,
                            initializer=_worker_initializer,
                            initargs=(self._dataset,))
        finally:
            if prev is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = prev
        self._proc_pool = pool
        self._proc_pool_method = method
        _WORKERS_GAUGE.set(self._num_workers)
        # terminate workers when the loader is garbage collected (or at
        # interpreter exit) — __del__ alone is not reliable enough for
        # child processes.  The finalizer carries the stop-event set (no
        # strong ref back to self) so a teardown that fires while an
        # epoch iterator is still alive does not deadlock on the
        # task-handler join.
        self._pool_finalizer = weakref.finalize(
            self, _terminate_pool, pool, self._active_stops)
        return pool

    def _shutdown_proc_pool(self):
        for s in list(self._active_stops):
            s.set()   # see _terminate_pool: unblock gated() before join
        if self._pool_finalizer is not None:
            self._pool_finalizer()  # terminates + joins, idempotent
            self._pool_finalizer = None
        if self._proc_pool is not None:
            _WORKERS_GAUGE.set(0)   # a scrape after close() must not
        self._proc_pool = None      # report the dead pool as live
        self._proc_pool_method = None

    def _abandon_proc_pool(self):
        """Discard a pool poisoned by an abnormal worker death.  A
        SIGKILLed child may have died holding a shared queue lock, so the
        orderly terminate+join of ``_shutdown_proc_pool`` can deadlock
        the parent: instead detach the finalizer (it must not re-run the
        blocking teardown at GC/exit), hard-kill the remaining children,
        and run the blocking teardown on a daemon thread — the iterator
        raises immediately and interpreter exit is never held hostage."""
        pool = self._proc_pool
        if pool is None:
            return
        if self._pool_finalizer is not None:
            self._pool_finalizer.detach()
            self._pool_finalizer = None
        self._proc_pool = None
        self._proc_pool_method = None
        _WORKERS_GAUGE.set(0)
        for p in list(pool._pool):
            try:
                p.kill()
            except Exception:  # already reaped
                pass
        threading.Thread(target=_terminate_pool, args=(pool,),
                         daemon=True).start()

    def close(self):
        """Release the persistent worker processes now instead of at GC /
        interpreter exit.  The loader remains usable — the next process-
        worker epoch starts a fresh pool.  Also usable as a context
        manager: ``with DataLoader(...) as dl: ...``."""
        self._shutdown_proc_pool()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _threaded_iter(self):
        pool = ThreadPoolExecutor(max_workers=self._num_workers)
        batches = list(self._epoch_batches())

        def load(batch):
            return self._batchify_fn([self._dataset[i] for i in batch])

        try:
            futures = queue.Queue()
            it = iter(batches)
            # prime the prefetch window
            primed = 0
            for batch in it:
                futures.put(pool.submit(load, batch))
                primed += 1
                if primed >= self._prefetch:
                    break
            while not futures.empty():
                f = futures.get()
                try:
                    nxt = next(it)
                    futures.put(pool.submit(load, nxt))
                except StopIteration:
                    pass
                yield f.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
