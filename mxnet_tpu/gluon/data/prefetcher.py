"""Device prefetch: keep the next N batches in flight on the accelerator.

Reference analog: the dependency-engine overlap of the source paper's input
pipeline (PAPER §1 — the accelerator never waits on the host because staging
overlaps compute) and tf.data's ``prefetch_to_device`` (PAPERS.md).  A
background thread pulls batches from the source iterator and issues
**non-blocking** ``jax.device_put`` — transfers ride the DMA engines while
the previous step computes — so the consumer's ``data`` phase collapses to a
queue pop.

Depth is ``MXNET_PREFETCH_BUFFER`` (default 2: one batch transferring, one
ready; ``0`` disables and the iterator degrades to a plain pass-through
staging wrapper on the caller's thread).

Failure domain (PR 2 contract): the prefetch thread is a *consumer* of the
DataLoader's worker-liveness machinery — a SIGKILLed process worker raises
``MXNetError`` inside the thread within the liveness deadline, and that
error is re-raised to the training loop on its next batch request, never
swallowed and never a hang.  ``close()`` (also wired through a GC
finalizer) unblocks and joins the thread even when the consumer abandons
the epoch mid-way.
"""
from __future__ import annotations

import queue
import threading
import time as _time
import weakref

import numpy as _np

from ... import env as _env
from ... import telemetry as _telemetry
from ...base import MXNetError

__all__ = ["PrefetchIterator", "device_put_batch", "stage_leaf"]

_HITS = _telemetry.counter(
    "mxnet_prefetch_hits_total",
    "batch requests served from a ready (already prefetched) batch")
_MISSES = _telemetry.counter(
    "mxnet_prefetch_misses_total",
    "batch requests that had to wait on the prefetch pipeline")
_DEPTH = _telemetry.gauge(
    "mxnet_prefetch_depth",
    "batches staged and ready (of the most recently active prefetcher)")
_WAIT = _telemetry.histogram(
    "mxnet_prefetch_wait_seconds",
    "time the consumer blocked waiting for a prefetched batch")

_ITEM, _END, _ERR = 0, 1, 2


def stage_leaf(host, sharding):
    """Place ONE array under ``sharding`` — the single decision tree every
    staging path shares (prefetcher, ``TrainStep._stage_batch``), so the
    subtle multi-process placement logic cannot drift between copies:

    - ``sharding=None``: default device;
    - already a ``jax.Array`` with the target sharding: zero-copy pass;
    - single process: plain ``device_put`` (handles resharding too);
    - multi-process: the value is this process's LOCAL shard of the
      global batch — assemble per-addressable-shard (``device_put`` would
      raise on a sharding spanning non-addressable devices; same recipe
      as ``parallel.distributed._put``)."""
    import jax

    if sharding is None:
        return jax.device_put(host)
    if isinstance(host, jax.Array) and host.sharding == sharding:
        return host
    if jax.process_count() == 1:
        return jax.device_put(host, sharding)
    return jax.make_array_from_process_local_data(
        sharding, _np.asarray(host))


def device_put_batch(batch, sharding=None):
    """Stage one batch on device, non-blocking, preserving structure
    (tuple/list of NDArray/numpy leaves stay NDArray-wrapped so downstream
    Gluon code keeps working).

    ``sharding=None`` targets the default device; a ``NamedSharding``
    places the global batch (a training step's ``_batch_shard``).  In a
    multi-process job each process contributes its local batch and the
    global array is assembled per-process-addressable-shard (same recipe
    as ``parallel.distributed._put`` — no cross-host host round trip)."""
    import jax

    from ...ndarray.ndarray import NDArray

    def put(leaf):
        if isinstance(leaf, (tuple, list)):
            return type(leaf)(put(x) for x in leaf)
        host = leaf
        ctx = None
        if isinstance(leaf, NDArray):
            ctx = leaf.context
            host = leaf._get()
        elif not isinstance(host, (jax.Array, _np.ndarray)):
            return leaf  # labels/metadata that are not arrays pass through
        return NDArray._from_jax(stage_leaf(host, sharding), ctx)

    return put(batch)


def _drain(q):
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass


def _finalize(stop, q, thread):
    # module-level (no self ref) so the weakref finalizer cannot keep the
    # iterator alive; drain unblocks a producer stuck in put()
    stop.set()
    _drain(q)
    if thread is not None and thread.is_alive():
        thread.join(timeout=5)


class PrefetchIterator:
    """Wrap a batch iterator with an N-deep device-prefetch pipeline.

    Usage::

        it = PrefetchIterator(iter(loader), sharding=step._batch_shard)
        for x, y in it:
            loss = step(x, y)      # x/y already on device
        it.close()                 # or rely on the GC finalizer
    """

    def __init__(self, source, depth=None, sharding=None, stage_fn=None):
        if depth is None:
            # the tuning funnel (env pin > MXNET_TUNE=1 winner >
            # default); the env accessor is the fallback so a broken
            # tuning tier can never stall the input pipeline
            try:
                from ... import tuning as _tuning

                depth = int(_tuning.resolve("prefetch_buffer"))
            except Exception:
                depth = _env.prefetch_buffer()
        self._depth = max(0, int(depth))
        self._sharding = sharding
        self._stage = stage_fn or (
            lambda b: device_put_batch(b, sharding))
        self._source = iter(source)
        self._error = None
        self._done = False
        if self._depth == 0:
            # disabled: stage on the caller's thread, no pipeline
            self._q = None
            self._thread = None
            self._stop = None
            self._finalizer = None
            return
        self._q = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._producer, name="mxnet-prefetch", daemon=True)
        self._finalizer = weakref.finalize(
            self, _finalize, self._stop, self._q, self._thread)
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _put(self, msg):
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        try:
            for item in self._source:
                staged = self._stage(item)
                if not self._put((_ITEM, staged)):
                    return
            self._put((_END, None))
        except BaseException as e:  # incl. worker-liveness MXNetError
            self._error = e  # visible even if the sentinel put is raced
            self._put((_ERR, e))

    # -- consumer ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self._q is None:  # depth 0: plain staging pass-through
            try:
                return self._stage(next(self._source))
            except StopIteration:
                self._done = True
                raise
        t0 = _time.perf_counter()
        hit = not self._q.empty()
        while True:
            try:
                kind, val = self._q.get(timeout=0.2)
                break
            except queue.Empty:
                if self._thread is not None and not self._thread.is_alive():
                    # producer died without managing to enqueue a sentinel
                    self._done = True
                    if self._error is not None:
                        raise self._error
                    raise MXNetError(
                        "prefetch thread died without delivering a batch "
                        "or an error (crashed interpreter thread?)")
        _WAIT.observe(_time.perf_counter() - t0)
        _DEPTH.set(self._q.qsize())
        if kind == _ITEM:
            # count only delivered batches (the end-of-epoch sentinel
            # fetch is not a batch request)
            (_HITS if hit else _MISSES).inc()
            return val
        self._done = True
        if kind == _ERR:
            raise val
        raise StopIteration  # _END

    def close(self):
        """Stop the background thread and release the queue.  Idempotent;
        safe to call from ``finally`` while the producer is mid-put."""
        self._done = True
        if self._finalizer is not None:
            self._finalizer()  # runs _finalize exactly once
        self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
