"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from .prefetcher import *  # noqa: F401,F403
from . import vision

from .dataset import __all__ as _d
from .sampler import __all__ as _s
from .dataloader import __all__ as _l
from .prefetcher import __all__ as _p

__all__ = list(_d) + list(_s) + list(_l) + list(_p) + ["vision"]
