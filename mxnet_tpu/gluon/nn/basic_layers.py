"""Gluon basic layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` (Dense, Dropout,
BatchNorm, InstanceNorm, LayerNorm, Embedding, Flatten, Lambda,
Sequential/HybridSequential — SURVEY.md §3.5 "Gluon layers").
"""
from __future__ import annotations

import numpy as _np

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack of Blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __len__(self):
        return len(self._children)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (reference: gluon.nn.Dense over the
    FullyConnected op, weight layout (units, in_units))."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self.weight = self.params.get("weight", shape=(units, in_units),
                                      init=weight_initializer, dtype=dtype,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(units,),
                                        init=bias_initializer, dtype=dtype,
                                        allow_deferred_init=True)
        else:
            self.bias = None
        self.act = Activation(activation, prefix=activation + "_") if activation else None
        if self.act is not None:
            self.register_child(self.act, "act")

    def infer_shape(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes or None,
                         training=autograd.is_training())


class BatchNorm(HybridBlock):
    """Batch normalization with running stats (reference:
    gluon.nn.BatchNorm over src/operator/nn/batch_norm.cc).  The moving
    mean/var updates thread through the functional-state mechanism."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)
        self.running_mean = self.params.get("running_mean", grad_req="null",
                                            shape=(in_channels,),
                                            init=running_mean_initializer,
                                            allow_deferred_init=True,
                                            differentiable=False)
        self.running_var = self.params.get("running_var", grad_req="null",
                                           shape=(in_channels,),
                                           init=running_variance_initializer,
                                           allow_deferred_init=True,
                                           differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = autograd.is_training() and not self._use_global_stats
        out, new_mean, new_var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, use_global_stats=self._use_global_stats,
            axis=self._axis, training=training)
        if training:
            self._update_running_state(self.running_mean, new_mean)
            self._update_running_state(self.running_var, new_var)
        return out


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else getattr(
            function, "__name__", "custom")
        if isinstance(function, str):
            def f(F, *args):
                return getattr(F, function)(*args)
            self._func = f
        else:
            self._func = function

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)


from .activations import Activation  # noqa: E402  (circular-free tail import)
