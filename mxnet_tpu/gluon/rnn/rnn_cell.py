"""RNN cells (reference: python/mxnet/gluon/rnn/rnn_cell.py — RNNCell,
LSTMCell, GRUCell, Residual/Dropout/Zoneout modifiers, unroll helpers)."""
from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F

        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            states.append(F.zeros(shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            step_in = F.squeeze(F.slice_axis(inputs, axis=axis, begin=i, end=i + 1),
                                axis=axis)
            out, states = self(step_in, states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, x, states):
        self._counter += 1
        return super().forward(x, states) if False else self._forward_cell(x, states)

    def _forward_cell(self, x, states):
        params = self._resolve_params(x)
        from ... import ndarray as F

        return self.hybrid_forward(F, x, states, **params)


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        prev_h, prev_c = states
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * prev_c + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def _forward_cell(self, x, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, s = cell(x, states[pos:pos + n])
            pos += n
            next_states.extend(s)
        return x, next_states

    def __len__(self):
        return len(self._children)


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class DropoutCell(ModifierCell):
    def __init__(self, rate, axes=(), **kwargs):
        cell = kwargs.pop("base_cell", None)
        if cell is None:
            raise MXNetError("DropoutCell here wraps a base_cell; pass "
                             "base_cell=...")
        super().__init__(cell)
        self._rate = rate
        self._axes = axes

    def _forward_cell(self, x, states):
        from ... import ndarray as F

        out, states = self.base_cell(x, states)
        if self._rate > 0:
            out = F.Dropout(out, p=self._rate, axes=self._axes or None,
                            training=autograd.is_training())
        return out, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def _forward_cell(self, x, states):
        from ... import ndarray as F

        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            if self._zo > 0:
                mask = F.random.bernoulli(prob=1 - self._zo, shape=out.shape)
                prev = self._prev_output if self._prev_output is not None else \
                    F.zeros_like(out)
                out = mask * out + (1 - mask) * prev
            if self._zs > 0:
                mixed = []
                for new, old in zip(next_states, states):
                    mask = F.random.bernoulli(prob=1 - self._zs, shape=new.shape)
                    mixed.append(mask * new + (1 - mask) * old)
                next_states = mixed
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    def _forward_cell(self, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states
