"""Fused recurrent layers: RNN / LSTM / GRU.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` over the fused RNN op
(``src/operator/rnn.cc`` + cuDNN rnn — SURVEY.md §3.2 "RNN"): multi-layer,
optionally bidirectional, whole-sequence in one kernel.

TPU-native: the time loop is ``lax.scan`` inside one pure function — XLA
compiles the scanned cell into a single fused loop (what the reference needed
cuDNN's monolithic kernel for).  The input projection for ALL timesteps is
batched into one (T·N, in) × (in, G·nh) matmul per layer/direction so the MXU
sees large GEMMs; only the recurrent h2h matmul stays inside the scan.  The
whole computation lands on the autograd tape as one node (apply_fn), giving
fused backward exactly like the reference's stateful RNN op.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ...ndarray.ndarray import apply_fn
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, gates,
                 activation=None, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = gates
        self._activation = activation
        ng, ni, nh = gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if bidirectional else ["l"]):
                setattr(self, f"{j}{i}_i2h_weight",
                        self.params.get(f"{j}{i}_i2h_weight", shape=(ng * nh, ni),
                                        init=i2h_weight_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_weight",
                        self.params.get(f"{j}{i}_h2h_weight", shape=(ng * nh, nh),
                                        init=h2h_weight_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_i2h_bias",
                        self.params.get(f"{j}{i}_i2h_bias", shape=(ng * nh,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_bias",
                        self.params.get(f"{j}{i}_h2h_bias", shape=(ng * nh,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True))
            ni = nh * self._dir

    @property
    def _num_states(self):
        return 2 if self._mode == "lstm" else 1

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}] * self._num_states

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F

        return [F.zeros(info["shape"]) for info in self.state_info(batch_size)]

    def infer_shape(self, x, *args):
        ni = x.shape[-1]
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                p = getattr(self, f"{j}{i}_i2h_weight")
                p.shape = (self._gates * self._hidden_size, ni)
            ni = self._hidden_size * self._dir

    # -- pure scan kernel --------------------------------------------------
    def _scan_one_direction(self, jnp, jax, xs, h0, c0, wi, wh, bi, bh):
        """xs: (T, N, ni). Returns (hs (T,N,nh), h_final, c_final|None)."""
        from jax import nn as jnn

        mode = self._mode
        i2h_all = jnp.einsum("tni,gi->tng", xs, wi) + bi

        if mode == "lstm":
            def step(carry, i2h_t):
                h_prev, c_prev = carry
                gates = i2h_t + h_prev @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jnn.sigmoid(i), jnn.sigmoid(f), jnn.sigmoid(o)
                c = f * c_prev + i * jnp.tanh(g)
                h = o * jnp.tanh(c)
                return (h, c), h

            (hf, cf), hs = jax.lax.scan(step, (h0, c0), i2h_all)
            return hs, hf, cf
        if mode == "gru":
            def step(h_prev, i2h_t):
                h2h = h_prev @ wh.T + bh
                ir, iz, in_ = jnp.split(i2h_t, 3, axis=-1)
                hr, hz, hn = jnp.split(h2h, 3, axis=-1)
                r = jnn.sigmoid(ir + hr)
                z = jnn.sigmoid(iz + hz)
                n = jnp.tanh(in_ + r * hn)
                h = (1 - z) * n + z * h_prev
                return h, h

            hf, hs = jax.lax.scan(step, h0, i2h_all)
            return hs, hf, None
        act = (lambda v: jnp.maximum(v, 0)) if self._activation == "relu" \
            else jnp.tanh

        def step(h_prev, i2h_t):
            h = act(i2h_t + h_prev @ wh.T + bh)
            return h, h

        hf, hs = jax.lax.scan(step, h0, i2h_all)
        return hs, hf, None

    def _rnn_pure(self, names, n_states, training, rng_key, xv, *rest):
        """Pure function: (x, *params, *states) -> (out, h_out[, c_out])."""
        import jax
        import jax.numpy as jnp

        pv = dict(zip(names, rest[:len(names)]))
        svals = list(rest[len(names):])
        if self._layout == "NTC":
            xv = jnp.swapaxes(xv, 0, 1)
        T, N, _ = xv.shape
        nh, nl, nd = self._hidden_size, self._num_layers, self._dir
        if not svals:
            svals = [jnp.zeros((nl * nd, N, nh), xv.dtype)
                     for _ in range(n_states)]
        out = xv
        out_h, out_c = [], []
        for layer in range(nl):
            layer_outs = []
            for d, tag in enumerate(["l", "r"][:nd]):
                idx = layer * nd + d
                seq = out if d == 0 else jnp.flip(out, axis=0)
                h0 = svals[0][idx]
                c0 = svals[1][idx] if self._mode == "lstm" else None
                hs, hf, cf = self._scan_one_direction(
                    jnp, jax, seq, h0, c0,
                    pv[f"{tag}{layer}_i2h_weight"], pv[f"{tag}{layer}_h2h_weight"],
                    pv[f"{tag}{layer}_i2h_bias"], pv[f"{tag}{layer}_h2h_bias"])
                if d == 1:
                    hs = jnp.flip(hs, axis=0)
                layer_outs.append(hs)
                out_h.append(hf)
                if cf is not None:
                    out_c.append(cf)
            out = layer_outs[0] if nd == 1 else \
                jnp.concatenate(layer_outs, axis=-1)
            if self._dropout > 0 and layer < nl - 1 and training:
                from jax import random as jr

                keep = 1.0 - self._dropout
                key = jr.fold_in(rng_key, layer)
                out = out * jr.bernoulli(key, keep, out.shape
                                         ).astype(out.dtype) / keep
        if self._layout == "NTC":
            out = jnp.swapaxes(out, 0, 1)
        outs = (out, jnp.stack(out_h, axis=0))
        if self._mode == "lstm":
            outs = outs + (jnp.stack(out_c, axis=0),)
        return outs

    def forward(self, x, states=None):
        from ... import autograd, random as _rnd

        params = self._resolve_params(x)
        names = sorted(params)
        state_args = list(states) if states is not None else []
        n_states = self._num_states
        training = autograd.is_training()
        rng_key = _rnd._next_key() if self._dropout > 0 else None

        def fn(xv, *rest):
            return self._rnn_pure(names, n_states, training, rng_key, xv, *rest)

        outs = apply_fn(fn, [x] + [params[n] for n in names] + state_args,
                        name=f"rnn:{self._mode}")
        out = outs[0]
        if states is None:
            return out
        return out, list(outs[1:])

    def hybrid_forward(self, F, x, states=None, **params):
        # used when traced inside an enclosing hybridized block
        return self.forward(x, states)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn", 1,
                         activation=activation, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", 4, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", 3, **kwargs)
