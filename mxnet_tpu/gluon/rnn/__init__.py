"""Gluon recurrent layers (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import *  # noqa: F401,F403
from .rnn_layer import *  # noqa: F401,F403

from .rnn_cell import __all__ as _c
from .rnn_layer import __all__ as _l

__all__ = list(_c) + list(_l)
