"""Gluon contrib (reference: python/mxnet/gluon/contrib/ — SyncBatchNorm,
Concurrent, Identity, estimator — SURVEY.md §3.5)."""
from . import nn
from .estimator import Estimator

__all__ = ["nn", "Estimator"]
