"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn.basic_layers import BatchNorm, Sequential, HybridSequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs (reference:
    gluon.contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F

        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: src/operator/contrib/
    sync_batch_norm.cc — the only activation-space collective in MXNet 1.x).

    TPU-native, two execution regimes:

    - **Sharded jit (TrainStep/hybridize over a mesh)**: the batch axis is
      global, so plain BatchNorm statistics reduce over the WHOLE global
      batch — GSPMD inserts the cross-device collective for the mean/var
      reductions (forward and backward).  Sync-BN is exact here for free.
    - **Eager multi-process (dist_tpu_sync-style jobs)**: the forward
      statistics are allreduced across processes (sum/sumsq/count), so the
      normalization and the running stats use the GLOBAL batch — the
      small-per-device-batch convergence story sync-BN exists for.  The
      backward treats the synced statistics as constants (the reference
      reduces the statistic gradients in a second collective; the jit path
      above gets those terms exactly, this eager path approximates them
      locally).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        # reference arg: ndev defaults to "all" — here the process count
        self._num_devices = num_devices

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        import jax

        from ...nn.basic_layers import autograd

        training = autograd.is_training() and not self._use_global_stats
        xv = x._get() if hasattr(x, "_get") else x
        eager = not isinstance(xv, jax.core.Tracer)
        nproc = jax.process_count()
        if not (training and eager and nproc > 1):
            # single process (reference ndev=1) or under jit (GSPMD syncs)
            return BatchNorm.hybrid_forward(self, F, x, gamma, beta,
                                            running_mean, running_var)

        import numpy as np

        from ....parallel.collectives import allreduce_hosts

        axis = self._axis % len(x.shape)
        axes = tuple(i for i in range(len(x.shape)) if i != axis)
        c = x.shape[axis]
        local_count = 1
        for i in axes:
            local_count *= x.shape[i]
        # forward-exact global statistics: one allreduce of [sum, sumsq, n]
        xs = x.asnumpy().astype("float64")
        stats = np.concatenate([xs.sum(axis=axes).ravel(),
                                (xs * xs).sum(axis=axes).ravel(),
                                [float(local_count)]])
        import jax.numpy as jnp

        g = np.asarray(allreduce_hosts(jnp.asarray(stats, jnp.float32)))
        n = g[-1]
        mean = g[:c] / n
        var = g[c:2 * c] / n - mean * mean
        bshape = [1] * len(x.shape)
        bshape[axis] = c
        mean_nd = F.array(mean.reshape(bshape).astype("float32"))
        std_nd = F.array(
            (1.0 / np.sqrt(var + self._epsilon))
            .reshape(bshape).astype("float32"))
        gam = gamma if self._scale else F.ones_like(gamma)
        out = (x - mean_nd) * std_nd * gam.reshape(bshape) \
            + beta.reshape(bshape)
        m = self._momentum
        new_mean = running_mean * m + F.array(mean.astype("float32")) * (1 - m)
        new_var = running_var * m + F.array(var.astype("float32")) * (1 - m)
        self._update_running_state(self.running_mean, new_mean)
        self._update_running_state(self.running_var, new_var)
        return out
