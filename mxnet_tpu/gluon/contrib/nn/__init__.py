"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn.basic_layers import BatchNorm, Sequential, HybridSequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs (reference:
    gluon.contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F

        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: src/operator/contrib/
    sync_batch_norm.cc — the only activation-space collective in MXNet 1.x).

    TPU-native: under a sharded jit step the batch axis is already global, so
    plain BatchNorm statistics computed inside shard_map with a psum ARE
    sync-BN; in the imperative single-process path this degenerates to
    BatchNorm (same as the reference with ndev=1).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
