"""Estimator train loop (reference 1.6: python/mxnet/gluon/contrib/estimator/)."""
from __future__ import annotations

import time

from ... import autograd
from ... import metric as metric_mod
from ... import telemetry as _telemetry
from ...base import MXNetError

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class Estimator:
    """Minimal fit() loop driving net/loss/trainer/metrics."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = [metric_mod.create(m) for m in
                              (train_metrics or ["accuracy"])]
        self.trainer = trainer
        self.context = context

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_size=None, telemetry=False):
        """``telemetry=True`` opens a telemetry timeline step per batch and
        attributes it to phases: ``data`` (iterator wait),
        ``forward_backward``, and ``optimizer`` (``trainer.step`` — which
        itself splits out ``collectives`` when the Trainer was built with
        ``telemetry=True``).  See :mod:`mxnet_tpu.telemetry`."""
        if self.trainer is None:
            raise MXNetError("Estimator needs a trainer")
        history = []
        for epoch in range(epochs):
            for m in self.train_metrics:
                m.reset()
            tic = time.time()
            nsamples = 0
            it = iter(train_data)
            while True:
                if telemetry:
                    _telemetry.step_begin()
                with _telemetry.maybe_phase(telemetry, "data"):
                    batch = next(it, None)
                if batch is None:
                    if telemetry:
                        _telemetry.step_abort()
                    break
                data, label = batch[0], batch[1]
                bs = data.shape[0]
                with _telemetry.maybe_phase(telemetry, "forward_backward"):
                    with autograd.record():
                        out = self.net(data)
                        loss = self.loss(out, label)
                    loss.backward()
                with _telemetry.maybe_phase(telemetry, "optimizer"):
                    self.trainer.step(bs)
                nsamples += bs
                for m in self.train_metrics:
                    m.update([label], [out])
                if telemetry:
                    _telemetry.step_end()
            elapsed = time.time() - tic
            stats = {name: val for name, val in
                     (m.get() for m in self.train_metrics)}
            stats["throughput"] = nsamples / max(elapsed, 1e-9)
            stats["epoch"] = epoch
            history.append(stats)
        return history
