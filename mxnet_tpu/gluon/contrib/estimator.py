"""Estimator train loop (reference 1.6: python/mxnet/gluon/contrib/estimator/)."""
from __future__ import annotations

import time

from ... import autograd
from ... import lifecycle as _lifecycle
from ... import metric as metric_mod
from ... import telemetry as _telemetry
from ...base import MXNetError

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class Estimator:
    """Minimal fit() loop driving net/loss/trainer/metrics."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = [metric_mod.create(m) for m in
                              (train_metrics or ["accuracy"])]
        self.trainer = trainer
        self.context = context
        # batches trained across fit() calls — the Estimator step counter
        # lifecycle.capture_train_state records; restore_train_state's
        # returned step is assigned back here on resume
        self.global_step = 0

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_size=None, telemetry=False, checkpoint_manager=None):
        """``telemetry=True`` opens a telemetry timeline step per batch and
        attributes it to phases: ``data`` (iterator wait),
        ``forward_backward``, and ``optimizer`` (``trainer.step`` — which
        itself splits out ``collectives`` when the Trainer was built with
        ``telemetry=True``).  See :mod:`mxnet_tpu.telemetry`.

        Preemption contract (:mod:`mxnet_tpu.lifecycle`): every batch
        boundary polls ``lifecycle.check_stop()`` (agreed across SPMD
        peers).  On a stop, a final SYNCHRONOUS checkpoint — net, trainer,
        and the exact-resume train_state (DataLoader position, RNG, step
        counter) — is published through ``checkpoint_manager`` (when one
        is passed and ``MXNET_PREEMPTION_CHECKPOINT`` allows), then
        ``lifecycle.GracefulExit`` is raised; ``run_with_recovery`` does
        not count it against the restart budget.

        The preemption checkpoint is numbered by ``global_step`` (the
        BATCH counter).  Checkpoint step numbers must be monotonic
        within one directory, so give fit its own manager/directory —
        do not mix it with a manager you save epoch-numbered
        checkpoints into, or an epoch save (small number) published
        after a batch-numbered preemption save (large number) makes
        ``latest_valid_step()`` resume the stale preemption point."""
        if self.trainer is None:
            raise MXNetError("Estimator needs a trainer")
        history = []
        for epoch in range(epochs):
            for m in self.train_metrics:
                m.reset()
            tic = time.time()
            nsamples = 0
            it = iter(train_data)
            while True:
                if telemetry:
                    _telemetry.step_begin()
                with _telemetry.maybe_phase(telemetry, "data"):
                    batch = next(it, None)
                if batch is None:
                    if telemetry:
                        _telemetry.step_abort()
                    break
                data, label = batch[0], batch[1]
                bs = data.shape[0]
                with _telemetry.maybe_phase(telemetry, "forward_backward"):
                    with autograd.record():
                        out = self.net(data)
                        loss = self.loss(out, label)
                    loss.backward()
                with _telemetry.maybe_phase(telemetry, "optimizer"):
                    self.trainer.step(bs)
                nsamples += bs
                self.global_step += 1
                for m in self.train_metrics:
                    m.update([label], [out])
                if telemetry:
                    _telemetry.step_end()
                if _lifecycle.check_stop():
                    self._stop_gracefully(train_data, checkpoint_manager)
            elapsed = time.time() - tic
            stats = {name: val for name, val in
                     (m.get() for m in self.train_metrics)}
            stats["throughput"] = nsamples / max(elapsed, 1e-9)
            stats["epoch"] = epoch
            history.append(stats)
        return history

    def _stop_gracefully(self, train_data, checkpoint_manager):
        """Honor an agreed preemption stop at a batch boundary: publish
        the final synchronous checkpoint (weights + optimizer + exact-
        resume train_state) and raise GracefulExit."""
        step = self.global_step
        if checkpoint_manager is not None:
            train_state = _lifecycle.capture_train_state(
                step=step,
                dataloader=train_data if hasattr(train_data, "state_dict")
                else None,
                trainer=self.trainer)
            _lifecycle.publish_final_checkpoint(
                checkpoint_manager, step, self.net, self.trainer,
                train_state=train_state)
        raise _lifecycle.GracefulExit(
            _lifecycle.stop_reason() or "stop requested", step=step)
