"""BERT as Gluon HybridBlocks (BASELINE config #2).

Reference placement: BERT lived in GluonNLP (external repo) on top of this
framework's ops — `src/operator/contrib/transformer.cc` provided the fused
interleaved matmuls it used (SURVEY.md §3.2).  Here the encoder rides the
same flash-attention kernel as Llama; BERT-base dims are the default.
"""
from __future__ import annotations

import math

from ...block import HybridBlock
from ... import nn

__all__ = ["BertConfig", "BertModel", "BertForPretraining", "bert_base",
           "bert_large", "bert_tiny"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_position=512,
                 type_vocab_size=2, dropout=0.1, layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.head_dim = hidden_size // num_heads


class BertSelfAttention(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        d = cfg.hidden_size
        self.query = nn.Dense(d, flatten=False, in_units=d)
        self.key = nn.Dense(d, flatten=False, in_units=d)
        self.value = nn.Dense(d, flatten=False, in_units=d)
        self.out = nn.Dense(d, flatten=False, in_units=d)
        self.dropout = nn.Dropout(cfg.dropout)

    def hybrid_forward(self, F, x):
        cfg = self._cfg
        b, l = x.shape[0], x.shape[1]
        hd = cfg.head_dim

        def heads(t):
            return t.reshape((b, l, cfg.num_heads, hd)).transpose((0, 2, 1, 3))

        q, k, v = heads(self.query(x)), heads(self.key(x)), heads(self.value(x))
        o = F.flash_attention(q, k, v, causal=False,
                              sm_scale=1.0 / math.sqrt(hd))
        o = o.transpose((0, 2, 1, 3)).reshape((b, l, cfg.hidden_size))
        return self.dropout(self.out(o))


class BertLayer(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.attention = BertSelfAttention(cfg)
        self.attn_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
        self.intermediate = nn.Dense(cfg.intermediate_size, flatten=False,
                                     in_units=cfg.hidden_size)
        self.output = nn.Dense(cfg.hidden_size, flatten=False,
                               in_units=cfg.intermediate_size)
        self.out_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.dropout)

    def hybrid_forward(self, F, x):
        x = self.attn_norm(x + self.attention(x))
        h = F.gelu(self.intermediate(x))
        return self.out_norm(x + self.dropout(self.output(h)))


class BertModel(HybridBlock):
    def __init__(self, cfg=None, **kwargs):
        super().__init__(**kwargs)
        cfg = cfg or BertConfig()
        self._cfg = cfg
        self.word_embed = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embed = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.token_type_embed = nn.Embedding(cfg.type_vocab_size,
                                             cfg.hidden_size)
        self.embed_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
        self.embed_dropout = nn.Dropout(cfg.dropout)
        self.encoder = nn.HybridSequential(prefix="")
        for _ in range(cfg.num_layers):
            self.encoder.add(BertLayer(cfg))
        self.pooler = nn.Dense(cfg.hidden_size, activation="tanh",
                               flatten=False, in_units=cfg.hidden_size)

    def hybrid_forward(self, F, input_ids, token_types=None):
        b, l = input_ids.shape[0], input_ids.shape[1]
        pos = F.arange(0, l, dtype="int32")
        h = self.word_embed(input_ids)
        positions = self.position_embed(pos)
        h = h + positions.reshape((1, l, -1))
        if token_types is not None:
            h = h + self.token_type_embed(token_types)
        h = self.embed_dropout(self.embed_norm(h))
        h = self.encoder(h)
        pooled = self.pooler(h.slice_axis(axis=1, begin=0, end=1)
                             .reshape((b, -1)))
        return h, pooled


class BertForPretraining(HybridBlock):
    """MLM + NSP heads over BertModel (GluonNLP BERTForPretrain shape)."""

    def __init__(self, cfg=None, **kwargs):
        super().__init__(**kwargs)
        cfg = cfg or BertConfig()
        self._cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_dense = nn.Dense(cfg.hidden_size, flatten=False,
                                  in_units=cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps)
        self.mlm_decoder = nn.Dense(cfg.vocab_size, flatten=False,
                                    in_units=cfg.hidden_size)
        self.nsp = nn.Dense(2, flatten=False, in_units=cfg.hidden_size)

    def hybrid_forward(self, F, input_ids, token_types=None):
        seq, pooled = self.bert(input_ids, token_types)
        mlm = self.mlm_decoder(self.mlm_norm(F.gelu(self.mlm_dense(seq))))
        return mlm, self.nsp(pooled)

    def pipeline_decompose(self, n_stages, train_mode=True):
        """Split BertForPretraining for TrainStep(pipeline=...): embeddings
        (pre) -> n_stages uniform encoder stages -> pooler + MLM/NSP heads
        (post).  Same contract as LlamaForCausalLM.pipeline_decompose.

        Notes: token_types input is not threaded (the bench/pretrain path
        passes ids only).  Dropout keys differ from the monolithic trace:
        the pipelined trunk folds (stage, layer) into the key — distinct
        masks per layer, shared across microbatches (the 1F1B recompute
        must reproduce forward masks exactly) — so use dropout=0 when
        bit-matching trajectories against the plain path.
        """
        from ....base import MXNetError
        from ....ops.registry import OP_TABLE
        from ....parallel.functional import functionalize

        cfg = self._cfg
        L = cfg.num_layers
        if L % n_stages:
            raise MXNetError(
                f"num_layers {L} not divisible by pipeline stages {n_stages}")
        bert = self.bert
        f = lambda blk: functionalize(blk, train_mode=train_mode)
        we, we_p = f(bert.word_embed)
        pe, pe_p = f(bert.position_embed)
        en, en_p = f(bert.embed_norm)
        do, do_p = f(bert.embed_dropout)
        lay0 = bert.encoder[0]
        lay, lay0_p = f(lay0)
        po, po_p = f(bert.pooler)
        md, md_p = f(self.mlm_dense)
        mn, mn_p = f(self.mlm_norm)
        mdec, mdec_p = f(self.mlm_decoder)
        nsp, nsp_p = f(self.nsp)
        gelu = OP_TABLE["gelu"].fn

        # construction-order mapping: identical blocks declare parameters in
        # the same order, while auto-generated name prefixes (dense7_, ...)
        # differ per instance — positional zip is the stable correspondence
        lay0_order = list(lay0.collect_params())
        layer_names = []
        for i in range(L):
            blk_order = list(bert.encoder[i].collect_params())
            layer_names.append(dict(zip(lay0_order, blk_order,
                                        strict=True)))

        def pre_fn(psub, rng, ids):
            import jax.numpy as jnp

            l = ids.shape[1]
            h = we({k: psub[k] for k in we_p}, rng, ids)
            pos = pe({k: psub[k] for k in pe_p}, rng,
                     jnp.arange(l, dtype=jnp.int32))
            h = h + pos.reshape((1, l, -1))
            h = en({k: psub[k] for k in en_p}, rng, h)
            return do({k: psub[k] for k in do_p}, rng, h)

        def layer_fn(pl, rng, h):
            return lay(pl, rng, h)

        def post_fn(psub, rng, h):
            pooled = po({k: psub[k] for k in po_p}, rng, h[:, 0, :])
            mlm = md({k: psub[k] for k in md_p}, rng, h)
            mlm = mn({k: psub[k] for k in mn_p}, rng, gelu(mlm))
            mlm = mdec({k: psub[k] for k in mdec_p}, rng, mlm)
            return mlm, nsp({k: psub[k] for k in nsp_p}, rng, pooled)

        return {
            "pre_names": list(we_p) + list(pe_p) + list(en_p) + list(do_p),
            "post_names": (list(po_p) + list(md_p) + list(mn_p)
                           + list(mdec_p) + list(nsp_p)),
            "layer_names": layer_names,
            "layer0_names": list(lay0_p),
            "pre_fn": pre_fn,
            "layer_fn": layer_fn,
            "post_fn": post_fn,
        }


def bert_base(**overrides):
    return BertModel(BertConfig(**overrides))


def bert_large(**overrides):
    kw = dict(hidden_size=1024, num_layers=24, num_heads=16,
              intermediate_size=4096)
    kw.update(overrides)
    return BertModel(BertConfig(**kw))


def bert_tiny(**overrides):
    kw = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=2,
              intermediate_size=128, max_position=128)
    kw.update(overrides)
    return BertModel(BertConfig(**kw))


