"""Llama-3-family decoder as Gluon HybridBlocks.

Net-new vs the reference (MXNet 1.x predates LLMs — SURVEY.md §6.7); this is
BASELINE config #5: "Llama-3-8B under Gluon HybridBlock, stressing
hybridize()→HLO".  TPU-first choices: RMSNorm/RoPE/SwiGLU as registry ops
(fp32 accumulation inside, bf16 activations outside), attention through the
flash-attention kernel (ops/flash_attention.py), weights laid out so tp/fsdp
sharding specs map cleanly onto the two matmul dimensions.
"""
from __future__ import annotations

import math

import numpy as _np

from ....base import MXNetError
from ...block import HybridBlock
from ...parameter import Parameter
from ... import nn

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama3_8b",
           "llama_tiny", "RMSNorm", "serving_params", "prefill_apply",
           "decode_apply"]


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096, num_layers=32,
                 num_heads=32, num_kv_heads=8, intermediate_size=14336,
                 rope_base=500000.0, max_seq_len=8192, rms_eps=1e-5,
                 dtype="float32", tie_embeddings=False, remat=False,
                 num_experts=0, moe_capacity_factor=1.25,
                 moe_aux_loss_weight=0.01):
        # num_experts > 0: Mixtral-style MoE FFN (switch top-1 routing,
        # parallel.expert_parallel) replaces the dense SwiGLU MLP; shard
        # the expert dim over the 'ep' mesh axis in TrainStep specs
        self.num_experts = num_experts
        self.moe_capacity_factor = moe_capacity_factor
        # Switch load-balance loss coefficient, injected into the backward
        # via parallel.expert_parallel.inject_aux_loss (0 disables)
        self.moe_aux_loss_weight = moe_aux_loss_weight
        # remat: rematerialize each decoder layer's activations in backward
        # (jax.checkpoint) — trades ~1/3 more FLOPs for O(num_layers) less
        # activation HBM, the standard lever for bigger per-chip batches
        self.remat = remat
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.intermediate_size = intermediate_size
        self.rope_base = rope_base
        self.max_seq_len = max_seq_len
        self.rms_eps = rms_eps
        self.dtype = dtype
        self.tie_embeddings = tie_embeddings
        if hidden_size % num_heads:
            raise MXNetError(
                f"num_heads ({num_heads}) must divide hidden_size "
                f"({hidden_size})")
        if num_heads % num_kv_heads:
            raise MXNetError(
                f"num_kv_heads ({num_kv_heads}) must divide num_heads "
                f"({num_heads}) for GQA")
        self.head_dim = hidden_size // num_heads


class RMSNorm(HybridBlock):
    def __init__(self, dim, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        self.weight = self.params.get("weight", shape=(dim,), init="ones")

    def hybrid_forward(self, F, x, weight):
        return F.rms_norm(x, weight, eps=self._eps)


class LlamaAttention(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        d, hd = cfg.hidden_size, cfg.head_dim
        self._cfg = cfg
        # child names matter: parallel.tensor_parallel's Megatron rules key
        # on the q/k/v/o_proj suffixes to pick column- vs row-parallel specs
        with self.name_scope():
            self.q_proj = nn.Dense(cfg.num_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="q_proj_")
            self.k_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="k_proj_")
            self.v_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="v_proj_")
            self.o_proj = nn.Dense(d, use_bias=False, flatten=False,
                                   in_units=cfg.num_heads * hd,
                                   prefix="o_proj_")

    def hybrid_forward(self, F, x):
        cfg = self._cfg
        b, l = x.shape[0], x.shape[1]
        hd = cfg.head_dim
        q = self.q_proj(x).reshape((b, l, cfg.num_heads, hd)).transpose(
            (0, 2, 1, 3))
        k = self.k_proj(x).reshape((b, l, cfg.num_kv_heads, hd)).transpose(
            (0, 2, 1, 3))
        v = self.v_proj(x).reshape((b, l, cfg.num_kv_heads, hd)).transpose(
            (0, 2, 1, 3))
        q = F.rope(q, base=cfg.rope_base)
        k = F.rope(k, base=cfg.rope_base)
        o = F.flash_attention(q, k, v, causal=True,
                              sm_scale=1.0 / math.sqrt(hd))
        o = o.transpose((0, 2, 1, 3)).reshape((b, l, cfg.num_heads * hd))
        return self.o_proj(o)


class LlamaMLP(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                      flatten=False, in_units=cfg.hidden_size,
                                      prefix="gate_proj_")
            self.up_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="up_proj_")
            self.down_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.intermediate_size,
                                      prefix="down_proj_")

    def hybrid_forward(self, F, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaMoEMLP(HybridBlock):
    """Switch-MoE SwiGLU FFN (Mixtral-style; net-new vs the reference).

    Expert weights are stacked with a leading expert axis so
    parallel.expert_parallel's dispatch/combine einsums (and the ep
    sharding) apply directly."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        E, H, I = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
        with self.name_scope():
            self.router = self.params.get("router_weight", shape=(H, E))
            self.gate_proj = self.params.get("gate_proj_weight",
                                             shape=(E, H, I))
            self.up_proj = self.params.get("up_proj_weight", shape=(E, H, I))
            self.down_proj = self.params.get("down_proj_weight",
                                             shape=(E, I, H))

    def hybrid_forward(self, F, x, router, gate_proj, up_proj, down_proj):
        # a registered op (not a raw apply_fn), so the block traces to
        # Symbol and exports/imports like the rest of the zoo
        cfg = self._cfg
        return F.moe_swiglu(x, router, gate_proj, up_proj, down_proj,
                            capacity_factor=cfg.moe_capacity_factor,
                            aux_loss_weight=cfg.moe_aux_loss_weight)


class LlamaDecoderLayer(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._remat = cfg.remat
        with self.name_scope():
            self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                           prefix="input_layernorm_")
            self.self_attn = LlamaAttention(cfg, prefix="self_attn_")
            self.post_attention_layernorm = RMSNorm(
                cfg.hidden_size, cfg.rms_eps,
                prefix="post_attention_layernorm_")
            if cfg.num_experts > 0:
                self.mlp = LlamaMoEMLP(cfg, prefix="mlp_")
            else:
                self.mlp = LlamaMLP(cfg, prefix="mlp_")

    def _body(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))

    def hybrid_forward(self, F, x):
        if self._remat:
            import jax

            from ....ndarray.ndarray import NDArray

            xv = x._get() if isinstance(x, NDArray) else x
            if isinstance(xv, jax.core.Tracer):
                # under a jax trace (TrainStep's fused step, or any
                # jax.jit/grad over the functionalized net): checkpoint the
                # whole layer — closed-over parameter tracers differentiate
                # normally, activations are recomputed in backward
                def body_pure(v):
                    return self._body(
                        NDArray._from_jax(v, getattr(x, "context", None))
                    )._get()

                out = jax.checkpoint(body_pure)(xv)
                return NDArray._from_jax(out, getattr(x, "context", None))
            # eager tape (autograd.record) and hybridize() both lack a
            # remat node — warn rather than silently skipping the memory
            # saving the user asked for
            from .... import autograd as _ag

            if type(x).__name__ == "SymbolTracer" or _ag.is_recording():
                import warnings

                warnings.warn(
                    "LlamaConfig(remat=True) has no effect under "
                    "hybridize() or the eager autograd tape; use "
                    "parallel.data_parallel.TrainStep (or jax.jit over "
                    "the functionalized net) for rematerialized training",
                    stacklevel=2)
        return self._body(x)


class LlamaModel(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                             prefix="embed_tokens_")
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for i in range(cfg.num_layers):
                    self.layers.add(LlamaDecoderLayer(cfg, prefix=f"{i}_"))
            self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps, prefix="norm_")

    def hybrid_forward(self, F, input_ids):
        h = self.embed_tokens(input_ids)
        h = self.layers(h)
        return self.norm(h)


class LlamaForCausalLM(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.model = LlamaModel(cfg, prefix="model_")
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="lm_head_")

    def hybrid_forward(self, F, input_ids):
        return self.lm_head(self.model(input_ids))

    @property
    def config(self):
        return self._cfg

    # -- incremental (KV-cached) decode -----------------------------------
    def init_decode_cache(self, batch, max_len=None):
        """Dense per-layer KV cache for :meth:`decode_step`.

        Returns ``{"k", "v"}`` of shape (num_layers, batch, num_kv_heads,
        max_len, head_dim) in the parameter dtype, plus ``"len"`` (tokens
        cached so far; uniform across the batch for this dense API — the
        serving engine's paged pool tracks per-row positions instead)."""
        import jax.numpy as jnp

        cfg = self._cfg
        max_len = max_len or cfg.max_seq_len
        dt = self.model.embed_tokens.weight.data().dtype
        shape = (cfg.num_layers, batch, cfg.num_kv_heads, max_len,
                 cfg.head_dim)
        return {"k": jnp.zeros(shape, dtype=dt),
                "v": jnp.zeros(shape, dtype=dt), "len": 0}

    def prefill(self, ids, cache):
        """Run the prompt through the full-context forward, seed ``cache``
        with every layer's roped k/v, and return the logits (B, L, V) —
        the same values ``self(ids)`` produces."""
        from ....ndarray.ndarray import NDArray

        ids_v = ids._get() if isinstance(ids, NDArray) else \
            _np.asarray(ids)
        logits, ks, vs = prefill_apply(serving_params(self), self._cfg,
                                       ids_v)
        L = ids_v.shape[1]
        cache["k"] = cache["k"].at[:, :, :, :L, :].set(
            ks.astype(cache["k"].dtype))
        cache["v"] = cache["v"].at[:, :, :, :L, :].set(
            vs.astype(cache["v"].dtype))
        cache["len"] = L
        from ....context import current_context

        return NDArray._from_jax(logits, current_context())

    def decode_step(self, ids, cache, positions=None):
        """Single-token forward against the cache: feeds ``ids`` (B,) at
        ``positions`` (default: ``cache["len"]`` for every row), writes
        the new k/v in, advances ``cache["len"]``, and returns logits
        (B, V) that bit-match ``self(full_ids)`` at the same position."""
        import jax.numpy as jnp

        from ....context import current_context
        from ....ndarray.ndarray import NDArray

        ids_v = ids._get() if isinstance(ids, NDArray) else \
            jnp.asarray(_np.asarray(ids))
        b = ids_v.shape[0]
        if positions is None:
            pos = jnp.full((b,), cache["len"], dtype=jnp.int32)
            advance = True
        else:
            pos = jnp.asarray(positions).astype(jnp.int32)
            advance = False

        def join(i, k_new, v_new):
            bi = jnp.arange(b)
            cache["k"] = cache["k"].at[i, bi, :, pos, :].set(
                k_new[:, :, 0, :].astype(cache["k"].dtype))
            cache["v"] = cache["v"].at[i, bi, :, pos, :].set(
                v_new[:, :, 0, :].astype(cache["v"].dtype))
            return cache["k"][i], cache["v"][i], pos + 1

        logits = decode_apply(serving_params(self), self._cfg, ids_v, pos,
                              join)
        if advance:
            cache["len"] += 1
        return NDArray._from_jax(logits, current_context())

    def pipeline_decompose(self, n_stages, train_mode=True):
        """Split the net for pipeline parallelism: embed (pre) ->
        ``n_stages`` homogeneous trunk stages of ``num_layers/n_stages``
        decoder layers each -> final norm + lm_head (post).

        The heterogeneous ends run OUTSIDE the pp loop (replicated /
        dp-sharded), the uniform trunk streams through
        ``parallel.pipeline_parallel.pipeline_apply`` — consumed by
        ``TrainStep(pipeline=...)``.

        Returns a dict: ``pre_names``/``post_names`` (parameter-name
        groups), ``layer_names`` (per layer, {layer0-name: this-layer
        name}), and pure ``pre_fn(params_sub, rng, ids)``,
        ``layer_fn(layer_params_keyed_like_layer0, rng, h)``,
        ``post_fn(params_sub, rng, h)``.
        """
        from ....parallel.functional import functionalize

        cfg = self._cfg
        L = cfg.num_layers
        if L % n_stages:
            raise MXNetError(
                f"num_layers {L} not divisible by pipeline stages "
                f"{n_stages}")
        model = self.model
        embed_apply, embed_p = functionalize(model.embed_tokens,
                                             train_mode=train_mode)
        lay0 = model.layers[0]
        lay_apply, lay0_p = functionalize(lay0, train_mode=train_mode)
        norm_apply, norm_p = functionalize(model.norm,
                                           train_mode=train_mode)
        head_apply, head_p = functionalize(self.lm_head,
                                           train_mode=train_mode)
        # construction-order mapping: identical blocks declare parameters
        # in the same order; positional zip is stable even when child
        # blocks carry auto-generated (globally counted) name prefixes
        lay0_order = list(lay0.collect_params())
        layer_names = []
        for i in range(L):
            blk_order = list(model.layers[i].collect_params())
            layer_names.append(dict(zip(lay0_order, blk_order,
                                        strict=True)))

        def pre_fn(psub, rng, ids):
            return embed_apply(psub, rng, ids)

        def layer_fn(pl, rng, h):
            return lay_apply(pl, rng, h)

        def post_fn(psub, rng, h):
            h = norm_apply({k: psub[k] for k in norm_p}, rng, h)
            return head_apply({k: psub[k] for k in head_p}, rng, h)

        return {
            "pre_names": list(embed_p),
            "post_names": list(norm_p) + list(head_p),
            "layer_names": layer_names,
            "layer0_names": list(lay0_p),
            "pre_fn": pre_fn,
            "layer_fn": layer_fn,
            "post_fn": post_fn,
        }


# ==========================================================================
# Incremental (KV-cached) decode — the serving-path forward (ISSUE 8).
#
# ``prefill_apply``/``decode_apply`` are *pure* functions over a
# structural-name parameter tree, written to mirror ``hybrid_forward``
# op-for-op (same registry-op bodies, same reshape/transpose order, same
# fp32 softmax with the flash-attention NEG_INF mask convention) so the
# single-token decode logits bit-match the full-context forward at every
# position.  ``mxnet_tpu.serving`` jit-compiles them against bucketed
# signatures (paged KV cache); the gluon-level ``LlamaForCausalLM.prefill``
# / ``decode_step`` run them eagerly against a dense cache for tests and
# small-scale use.
# ==========================================================================
def serving_params(net):
    """Structural-name parameter tree for the pure serving forwards.

    Keys are ``_collect_params_with_prefix`` block-path names
    (``model.layers.0.self_attn.q_proj.weight``) — stable across global
    auto-name prefixes, so an exported manifest binds to any instance of
    the same architecture.  Values are the live jax arrays (no copy)."""
    from collections import OrderedDict

    return OrderedDict(
        (name, p.data()._get())
        for name, p in sorted(net._collect_params_with_prefix().items()))


def _jnp():
    import jax.numpy as jnp

    return jnp


def _dense_nb(x, weight):
    """``F.FullyConnected(flatten=False, no_bias=True)`` body (ops/nn.py):
    weight layout (units, in_units)."""
    return _jnp().matmul(x, weight.T)


def _decode_attention(q, k, v, n_valid, sm_scale):
    """Single-query attention over a (padded) key context.

    Mirrors ``ops.flash_attention._mha_with_lse`` bit-for-bit for one
    query row: GQA repeat, fp32 scores, NEG_INF mask (``exp`` of it is
    exactly 0.0, so padded keys add exact zeros to the same softmax sum
    the full-context forward computes), max-shift softmax, value matmul
    in the value dtype.  ``n_valid`` (B,) counts valid keys per row —
    key j is visible iff ``j < n_valid`` ≡ the causal row of the
    full-context mask at position ``n_valid - 1``."""
    jnp = _jnp()
    from ....ops.flash_attention import NEG_INF

    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    # matmul, NOT einsum: at query length 1 XLA:CPU lowers the einsum
    # contraction through a different kernel whose d-axis accumulation
    # order diverges from the full-context einsum's rows (~1e-6); the
    # batched matmul reproduces the full-context rows bit-for-bit
    scores = jnp.matmul(q.astype(jnp.float32),
                        jnp.swapaxes(k.astype(jnp.float32), -1, -2)) \
        * sm_scale
    mask = jnp.arange(k.shape[2])[None, :] < n_valid[:, None]      # (B, S)
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    m = scores.max(axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    denom = e.sum(axis=-1, keepdims=True)
    p = e / denom
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _proj_qkv(params, cfg, pre, h, pos2):
    """q/k/v projections + rope for one attention block (shared by the
    prefill and decode paths so the cached k/v and the decode-step q are
    computed by literally the same code)."""
    from ....ops.attention_ops import rope as _rope

    jnp = _jnp()
    b, l = h.shape[0], h.shape[1]
    hd = cfg.head_dim
    q = _dense_nb(h, params[pre + "self_attn.q_proj.weight"]) \
        .reshape(b, l, cfg.num_heads, hd).transpose(0, 2, 1, 3)
    k = _dense_nb(h, params[pre + "self_attn.k_proj.weight"]) \
        .reshape(b, l, cfg.num_kv_heads, hd).transpose(0, 2, 1, 3)
    v = _dense_nb(h, params[pre + "self_attn.v_proj.weight"]) \
        .reshape(b, l, cfg.num_kv_heads, hd).transpose(0, 2, 1, 3)
    q = _rope(q, positions=pos2, base=cfg.rope_base)
    k = _rope(k, positions=pos2, base=cfg.rope_base)
    return q, k, v


def _mlp_block(params, cfg, pre, h):
    from jax import nn as _jnn

    g = _dense_nb(h, params[pre + "mlp.gate_proj.weight"])
    u = _dense_nb(h, params[pre + "mlp.up_proj.weight"])
    return _dense_nb(_jnn.silu(g) * u, params[pre + "mlp.down_proj.weight"])


def _embed(params, cfg, ids):
    """``F.Embedding`` body (ops/tensor.py): clip + take."""
    jnp = _jnp()
    idx = jnp.clip(ids.astype(jnp.int32), 0, cfg.vocab_size - 1)
    return jnp.take(params["model.embed_tokens.weight"], idx, axis=0)


def prefill_apply(params, cfg, ids):
    """Full-context forward that also returns every layer's roped k/v.

    ``ids`` (B, L) int32.  Returns ``(logits (B, L, V), k (num_layers, B,
    num_kv_heads, L, head_dim), v (same))`` — the logits are the same
    computation as ``LlamaForCausalLM.__call__`` (so right-padding a
    prompt never changes the logits at real positions: causal attention
    means position i only sees j <= i), and the k/v stacks seed a decode
    cache."""
    if cfg.num_experts > 0:
        raise MXNetError("incremental decode does not support MoE FFNs yet")
    jnp = _jnp()
    from ....ops.attention_ops import rms_norm as _rms
    from ....ops.flash_attention import flash_attention as _fa

    x = _embed(params, cfg, ids)
    b, l = x.shape[0], x.shape[1]
    hd = cfg.head_dim
    ks, vs = [], []
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        h = _rms(x, params[pre + "input_layernorm.weight"], eps=cfg.rms_eps)
        q, k, v = _proj_qkv(params, cfg, pre, h, None)
        ks.append(k)
        vs.append(v)
        o = _fa(q, k, v, causal=True, sm_scale=1.0 / math.sqrt(hd))
        o = o.transpose(0, 2, 1, 3).reshape(b, l, cfg.num_heads * hd)
        x = x + _dense_nb(o, params[pre + "self_attn.o_proj.weight"])
        h2 = _rms(x, params[pre + "post_attention_layernorm.weight"],
                  eps=cfg.rms_eps)
        x = x + _mlp_block(params, cfg, pre, h2)
    x = _rms(x, params["model.norm.weight"], eps=cfg.rms_eps)
    logits = _dense_nb(x, params["lm_head.weight"])
    return logits, jnp.stack(ks), jnp.stack(vs)


def decode_apply(params, cfg, ids, positions, kv_join):
    """One single-token decode step, pure.

    ``ids`` (B,) int32 — the tokens to feed; ``positions`` (B,) int32 —
    each row's sequence position.  ``kv_join(layer, k_new, v_new) ->
    (K, V, n_valid)`` owns the cache: it must merge the new roped
    k/v (B, num_kv_heads, 1, head_dim) into layer ``layer``'s context and
    return the full (padded) key/value arrays plus the per-row valid-key
    count (``positions + 1``).  Dense caches (``decode_step``) and the
    serving paged pool both plug in here, so there is exactly one copy of
    the decode math.  Returns logits (B, vocab)."""
    if cfg.num_experts > 0:
        raise MXNetError("incremental decode does not support MoE FFNs yet")
    jnp = _jnp()
    from ....ops.attention_ops import rms_norm as _rms

    hd = cfg.head_dim
    ids = jnp.asarray(ids)
    x = _embed(params, cfg, ids)[:, None, :]                      # (B, 1, d)
    b = x.shape[0]
    pos = jnp.asarray(positions).astype(jnp.int32)                # (B,)
    pos2 = pos[:, None]                                           # rope (B,1)
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        h = _rms(x, params[pre + "input_layernorm.weight"], eps=cfg.rms_eps)
        q, k, v = _proj_qkv(params, cfg, pre, h, pos2)
        K, V, n_valid = kv_join(i, k, v)
        o = _decode_attention(q, K, V, n_valid, 1.0 / math.sqrt(hd))
        o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.num_heads * hd)
        x = x + _dense_nb(o, params[pre + "self_attn.o_proj.weight"])
        h2 = _rms(x, params[pre + "post_attention_layernorm.weight"],
                  eps=cfg.rms_eps)
        x = x + _mlp_block(params, cfg, pre, h2)
    x = _rms(x, params["model.norm.weight"], eps=cfg.rms_eps)
    return _dense_nb(x, params["lm_head.weight"])[:, 0, :]        # (B, V)


def llama3_8b(**overrides):
    """The BASELINE config-#5 architecture (Llama-3-8B dims)."""
    return LlamaForCausalLM(LlamaConfig(**overrides))


def llama_tiny(**overrides):
    """Test/bench-scale Llama (same architecture, small dims)."""
    kw = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
              num_kv_heads=2, intermediate_size=256, max_seq_len=256)
    kw.update(overrides)
    return LlamaForCausalLM(LlamaConfig(**kw))
