"""Llama-3-family decoder as Gluon HybridBlocks.

Net-new vs the reference (MXNet 1.x predates LLMs — SURVEY.md §6.7); this is
BASELINE config #5: "Llama-3-8B under Gluon HybridBlock, stressing
hybridize()→HLO".  TPU-first choices: RMSNorm/RoPE/SwiGLU as registry ops
(fp32 accumulation inside, bf16 activations outside), attention through the
flash-attention kernel (ops/flash_attention.py), weights laid out so tp/fsdp
sharding specs map cleanly onto the two matmul dimensions.
"""
from __future__ import annotations

import math

import numpy as _np

from ....base import MXNetError
from ...block import HybridBlock
from ...parameter import Parameter
from ... import nn

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama3_8b",
           "llama_tiny", "RMSNorm"]


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096, num_layers=32,
                 num_heads=32, num_kv_heads=8, intermediate_size=14336,
                 rope_base=500000.0, max_seq_len=8192, rms_eps=1e-5,
                 dtype="float32", tie_embeddings=False, remat=False,
                 num_experts=0, moe_capacity_factor=1.25,
                 moe_aux_loss_weight=0.01):
        # num_experts > 0: Mixtral-style MoE FFN (switch top-1 routing,
        # parallel.expert_parallel) replaces the dense SwiGLU MLP; shard
        # the expert dim over the 'ep' mesh axis in TrainStep specs
        self.num_experts = num_experts
        self.moe_capacity_factor = moe_capacity_factor
        # Switch load-balance loss coefficient, injected into the backward
        # via parallel.expert_parallel.inject_aux_loss (0 disables)
        self.moe_aux_loss_weight = moe_aux_loss_weight
        # remat: rematerialize each decoder layer's activations in backward
        # (jax.checkpoint) — trades ~1/3 more FLOPs for O(num_layers) less
        # activation HBM, the standard lever for bigger per-chip batches
        self.remat = remat
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.intermediate_size = intermediate_size
        self.rope_base = rope_base
        self.max_seq_len = max_seq_len
        self.rms_eps = rms_eps
        self.dtype = dtype
        self.tie_embeddings = tie_embeddings
        if hidden_size % num_heads:
            raise MXNetError(
                f"num_heads ({num_heads}) must divide hidden_size "
                f"({hidden_size})")
        if num_heads % num_kv_heads:
            raise MXNetError(
                f"num_kv_heads ({num_kv_heads}) must divide num_heads "
                f"({num_heads}) for GQA")
        self.head_dim = hidden_size // num_heads


class RMSNorm(HybridBlock):
    def __init__(self, dim, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        self.weight = self.params.get("weight", shape=(dim,), init="ones")

    def hybrid_forward(self, F, x, weight):
        return F.rms_norm(x, weight, eps=self._eps)


class LlamaAttention(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        d, hd = cfg.hidden_size, cfg.head_dim
        self._cfg = cfg
        # child names matter: parallel.tensor_parallel's Megatron rules key
        # on the q/k/v/o_proj suffixes to pick column- vs row-parallel specs
        with self.name_scope():
            self.q_proj = nn.Dense(cfg.num_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="q_proj_")
            self.k_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="k_proj_")
            self.v_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=d,
                                   prefix="v_proj_")
            self.o_proj = nn.Dense(d, use_bias=False, flatten=False,
                                   in_units=cfg.num_heads * hd,
                                   prefix="o_proj_")

    def hybrid_forward(self, F, x):
        cfg = self._cfg
        b, l = x.shape[0], x.shape[1]
        hd = cfg.head_dim
        q = self.q_proj(x).reshape((b, l, cfg.num_heads, hd)).transpose(
            (0, 2, 1, 3))
        k = self.k_proj(x).reshape((b, l, cfg.num_kv_heads, hd)).transpose(
            (0, 2, 1, 3))
        v = self.v_proj(x).reshape((b, l, cfg.num_kv_heads, hd)).transpose(
            (0, 2, 1, 3))
        q = F.rope(q, base=cfg.rope_base)
        k = F.rope(k, base=cfg.rope_base)
        o = F.flash_attention(q, k, v, causal=True,
                              sm_scale=1.0 / math.sqrt(hd))
        o = o.transpose((0, 2, 1, 3)).reshape((b, l, cfg.num_heads * hd))
        return self.o_proj(o)


class LlamaMLP(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                      flatten=False, in_units=cfg.hidden_size,
                                      prefix="gate_proj_")
            self.up_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="up_proj_")
            self.down_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.intermediate_size,
                                      prefix="down_proj_")

    def hybrid_forward(self, F, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaMoEMLP(HybridBlock):
    """Switch-MoE SwiGLU FFN (Mixtral-style; net-new vs the reference).

    Expert weights are stacked with a leading expert axis so
    parallel.expert_parallel's dispatch/combine einsums (and the ep
    sharding) apply directly."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        E, H, I = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
        with self.name_scope():
            self.router = self.params.get("router_weight", shape=(H, E))
            self.gate_proj = self.params.get("gate_proj_weight",
                                             shape=(E, H, I))
            self.up_proj = self.params.get("up_proj_weight", shape=(E, H, I))
            self.down_proj = self.params.get("down_proj_weight",
                                             shape=(E, I, H))

    def hybrid_forward(self, F, x, router, gate_proj, up_proj, down_proj):
        # a registered op (not a raw apply_fn), so the block traces to
        # Symbol and exports/imports like the rest of the zoo
        cfg = self._cfg
        return F.moe_swiglu(x, router, gate_proj, up_proj, down_proj,
                            capacity_factor=cfg.moe_capacity_factor,
                            aux_loss_weight=cfg.moe_aux_loss_weight)


class LlamaDecoderLayer(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._remat = cfg.remat
        with self.name_scope():
            self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                           prefix="input_layernorm_")
            self.self_attn = LlamaAttention(cfg, prefix="self_attn_")
            self.post_attention_layernorm = RMSNorm(
                cfg.hidden_size, cfg.rms_eps,
                prefix="post_attention_layernorm_")
            if cfg.num_experts > 0:
                self.mlp = LlamaMoEMLP(cfg, prefix="mlp_")
            else:
                self.mlp = LlamaMLP(cfg, prefix="mlp_")

    def _body(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))

    def hybrid_forward(self, F, x):
        if self._remat:
            import jax

            from ....ndarray.ndarray import NDArray

            xv = x._get() if isinstance(x, NDArray) else x
            if isinstance(xv, jax.core.Tracer):
                # under a jax trace (TrainStep's fused step, or any
                # jax.jit/grad over the functionalized net): checkpoint the
                # whole layer — closed-over parameter tracers differentiate
                # normally, activations are recomputed in backward
                def body_pure(v):
                    return self._body(
                        NDArray._from_jax(v, getattr(x, "context", None))
                    )._get()

                out = jax.checkpoint(body_pure)(xv)
                return NDArray._from_jax(out, getattr(x, "context", None))
            # eager tape (autograd.record) and hybridize() both lack a
            # remat node — warn rather than silently skipping the memory
            # saving the user asked for
            from .... import autograd as _ag

            if type(x).__name__ == "SymbolTracer" or _ag.is_recording():
                import warnings

                warnings.warn(
                    "LlamaConfig(remat=True) has no effect under "
                    "hybridize() or the eager autograd tape; use "
                    "parallel.data_parallel.TrainStep (or jax.jit over "
                    "the functionalized net) for rematerialized training",
                    stacklevel=2)
        return self._body(x)


class LlamaModel(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                             prefix="embed_tokens_")
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for i in range(cfg.num_layers):
                    self.layers.add(LlamaDecoderLayer(cfg, prefix=f"{i}_"))
            self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps, prefix="norm_")

    def hybrid_forward(self, F, input_ids):
        h = self.embed_tokens(input_ids)
        h = self.layers(h)
        return self.norm(h)


class LlamaForCausalLM(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.model = LlamaModel(cfg, prefix="model_")
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="lm_head_")

    def hybrid_forward(self, F, input_ids):
        return self.lm_head(self.model(input_ids))

    @property
    def config(self):
        return self._cfg

    def pipeline_decompose(self, n_stages, train_mode=True):
        """Split the net for pipeline parallelism: embed (pre) ->
        ``n_stages`` homogeneous trunk stages of ``num_layers/n_stages``
        decoder layers each -> final norm + lm_head (post).

        The heterogeneous ends run OUTSIDE the pp loop (replicated /
        dp-sharded), the uniform trunk streams through
        ``parallel.pipeline_parallel.pipeline_apply`` — consumed by
        ``TrainStep(pipeline=...)``.

        Returns a dict: ``pre_names``/``post_names`` (parameter-name
        groups), ``layer_names`` (per layer, {layer0-name: this-layer
        name}), and pure ``pre_fn(params_sub, rng, ids)``,
        ``layer_fn(layer_params_keyed_like_layer0, rng, h)``,
        ``post_fn(params_sub, rng, h)``.
        """
        from ....parallel.functional import functionalize

        cfg = self._cfg
        L = cfg.num_layers
        if L % n_stages:
            raise MXNetError(
                f"num_layers {L} not divisible by pipeline stages "
                f"{n_stages}")
        model = self.model
        embed_apply, embed_p = functionalize(model.embed_tokens,
                                             train_mode=train_mode)
        lay0 = model.layers[0]
        lay_apply, lay0_p = functionalize(lay0, train_mode=train_mode)
        norm_apply, norm_p = functionalize(model.norm,
                                           train_mode=train_mode)
        head_apply, head_p = functionalize(self.lm_head,
                                           train_mode=train_mode)
        # construction-order mapping: identical blocks declare parameters
        # in the same order; positional zip is stable even when child
        # blocks carry auto-generated (globally counted) name prefixes
        lay0_order = list(lay0.collect_params())
        layer_names = []
        for i in range(L):
            blk_order = list(model.layers[i].collect_params())
            layer_names.append(dict(zip(lay0_order, blk_order,
                                        strict=True)))

        def pre_fn(psub, rng, ids):
            return embed_apply(psub, rng, ids)

        def layer_fn(pl, rng, h):
            return lay_apply(pl, rng, h)

        def post_fn(psub, rng, h):
            h = norm_apply({k: psub[k] for k in norm_p}, rng, h)
            return head_apply({k: psub[k] for k in head_p}, rng, h)

        return {
            "pre_names": list(embed_p),
            "post_names": list(norm_p) + list(head_p),
            "layer_names": layer_names,
            "layer0_names": list(lay0_p),
            "pre_fn": pre_fn,
            "layer_fn": layer_fn,
            "post_fn": post_fn,
        }


def llama3_8b(**overrides):
    """The BASELINE config-#5 architecture (Llama-3-8B dims)."""
    return LlamaForCausalLM(LlamaConfig(**overrides))


def llama_tiny(**overrides):
    """Test/bench-scale Llama (same architecture, small dims)."""
    kw = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
              num_kv_heads=2, intermediate_size=256, max_seq_len=256)
    kw.update(overrides)
    return LlamaForCausalLM(LlamaConfig(**kw))
