"""Language model zoo: Llama-3 family + BERT (BASELINE configs #2 and #5)."""
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM, llama3_8b,
                    llama_tiny, RMSNorm)
from .bert import (BertConfig, BertModel, BertForPretraining, bert_base,
                   bert_large, bert_tiny)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama3_8b",
           "llama_tiny", "RMSNorm", "BertConfig", "BertModel",
           "BertForPretraining", "bert_base", "bert_large", "bert_tiny"]
