"""Model zoo vision entrypoint (reference:
python/mxnet/gluon/model_zoo/vision/__init__.py get_model)."""
from ....base import MXNetError
from .resnet import *  # noqa: F401,F403
from .resnet import __all__ as _resnet_all

_models = {}
for _n in _resnet_all:
    if _n.startswith("resnet") and _n[6].isdigit():
        _models[_n] = globals()[_n]


def _register_lazy():
    """Models added as families land; get_model sees them automatically."""
    try:
        from . import alexnet as _a

        _models["alexnet"] = _a.alexnet
    except ImportError:
        pass
    try:
        from . import vgg as _v

        for n in _v.__all__:
            if n.startswith("vgg") and n[3].isdigit():
                _models[n] = getattr(_v, n)
    except ImportError:
        pass
    try:
        from . import mobilenet as _m

        for n in _m.__all__:
            if n.startswith("mobilenet") and not n[0].isupper():
                _models[n] = getattr(_m, n)
    except ImportError:
        pass
    try:
        from . import squeezenet as _s

        for n in _s.__all__:
            if n.startswith("squeezenet") and n[10].isdigit():
                _models[n] = getattr(_s, n)
    except ImportError:
        pass
    try:
        from . import densenet as _d

        for n in _d.__all__:
            if n.startswith("densenet") and n[8].isdigit():
                _models[n] = getattr(_d, n)
    except ImportError:
        pass
    try:
        from . import inception as _i

        _models["inceptionv3"] = _i.inception_v3
    except ImportError:
        pass


_register_lazy()


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(f"model {name} not found; available: {sorted(_models)}")
    return _models[name](**kwargs)
