"""Persistent warm-start compile cache: lowered executables on disk,
beside the checkpoints they resume with.

The second half of the zero-downtime-elasticity story (ROADMAP): a
restarted job used to pay a full retrace-and-compile of every
TrainStep/serving signature even when nothing about the program
changed.  This module persists the *serialized lowered executable*
(``jax.export``) keyed by the same vocabulary the PR 1/PR 8 dispatch
layers already use, so a warm resume loads executables instead of
tracing Python — **zero fresh traces**, asserted by the PR 3
compile-event tracer (a cache hit records a ``compile_cache`` hit
counter, never a compile event, because no trace happened).

Key = sha256 over:

- the consumer's :func:`~mxnet_tpu.ndarray.dispatch_cache.
  signature_key`-style components (avals + static extras + AMP epoch +
  ctx kind),
- the governing :class:`~mxnet_tpu.parallel.planner.ShardingPlan`
  digest (a re-planned mesh must never serve the old executable),
- the jax/jaxlib version fingerprint plus this module's format version
  (an upgraded runtime silently starts cold),
- ``MXNET_COMPILE_CACHE_SALT`` (manual invalidation for Python-side
  semantic changes the signature cannot see — a rewritten loss closure
  keeps its qualname; bump the salt or clear the directory).

Entry format: one file per key, ``<keyhash>.exe`` = a JSON header line
(payload sha256, sizes, jax fingerprint, creation time) + the
serialized executable bytes.  Written atomically (tmp + rename, the
checkpoint discipline), verified on read: **a corrupt, truncated, or
version-mismatched entry is a silent miss, never a crash** — the
consumer simply traces fresh and overwrites it.

Consumers: ``TrainStep(compile_cache=...)``,
``ServingEngine(..., compile_cache=...)``, both defaulting to the
session cache (``MXNET_COMPILE_CACHE_DIR``) when one is configured;
``CheckpointManager.compile_cache`` keeps one beside its checkpoints.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time

from . import env as _env
from . import telemetry as _telemetry

__all__ = ["CompileCache", "enabled", "default_cache", "resolve",
           "aval_signature"]

_LOGGER = logging.getLogger(__name__)

# bump when the on-disk format or the wrapping semantics change: old
# entries silently miss instead of deserializing garbage
_FORMAT_VERSION = 1

_HITS = _telemetry.counter(
    "mxnet_compile_cache_hits_total",
    "warm-start executables served from the persistent compile cache "
    "(each one is a trace+compile that did NOT happen)")
_MISSES = _telemetry.counter(
    "mxnet_compile_cache_misses_total",
    "compile-cache lookups that found no usable entry")
_CORRUPT = _telemetry.counter(
    "mxnet_compile_cache_corrupt_total",
    "cache entries rejected by verification (corrupt/truncated/"
    "version-mismatched) — each one degraded to a clean miss")
_STORES = _telemetry.counter(
    "mxnet_compile_cache_stores_total",
    "executables serialized into the persistent compile cache")


def enabled():
    """Whether compile caching may run at all (``MXNET_COMPILE_CACHE``,
    default on)."""
    return _env.compile_cache_enabled()


_DEFAULT = None
_DEFAULT_DIR = None


def default_cache():
    """The session-default cache from ``MXNET_COMPILE_CACHE_DIR`` (None
    when unset or caching is disabled)."""
    global _DEFAULT, _DEFAULT_DIR
    if not enabled():
        return None
    d = _env.compile_cache_dir()
    if not d:
        return None
    if _DEFAULT is None or _DEFAULT_DIR != d:
        _DEFAULT = CompileCache(d)
        _DEFAULT_DIR = d
    return _DEFAULT


def resolve(explicit):
    """The cache a consumer should use: an explicit ``CompileCache``
    argument wins; otherwise the session default; None = no caching."""
    if explicit is not None:
        return explicit if enabled() else None
    return default_cache()


def _jax_fingerprint():
    import jax
    import jaxlib

    return f"jax={jax.__version__};jaxlib={jaxlib.__version__}" \
           f";fmt={_FORMAT_VERSION}"


def aval_signature(tree):
    """Stable (treedef, leaves) fingerprint of a pytree of arrays /
    ShapeDtypeStructs, sharding included — the aval half of a cache
    key."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (str(treedef),
            tuple((tuple(v.shape), str(v.dtype),
                   str(getattr(v, "sharding", None)))
                  for v in leaves))


class CompileCache:
    """One on-disk executable cache directory (content-addressed,
    atomic-publish, sha256-verified)."""

    def __init__(self, directory, logger=None):
        self.directory = directory
        self.logger = logger or _LOGGER

    # -- keys --------------------------------------------------------------
    def key(self, name, components, plan_digest=None):
        """sha256 key for one executable: ``name`` (consumer kind +
        label), ``components`` (any repr-stable tuple — signature_key
        output, aval signatures, static config), the plan digest, the
        jax fingerprint, and the salt knob."""
        doc = repr((str(name), components, plan_digest or "none",
                    _jax_fingerprint(), _env.compile_cache_salt()))
        return hashlib.sha256(doc.encode()).hexdigest()

    def _path(self, key):
        return os.path.join(self.directory, f"{key}.exe")

    # -- raw entries -------------------------------------------------------
    def get_entry(self, key):
        """``(payload, meta)`` for a verified entry, or ``(None, {})``
        (miss).  ``meta`` is the caller-supplied sidecar from
        :meth:`put_bytes` — e.g. the compile-time FLOP count a warm
        load needs for online MFU accounting without re-deriving cost
        analysis.  Every failure mode — missing file, torn header,
        truncated payload, checksum mismatch, fingerprint drift — is a
        SILENT miss.  Counts misses/corruption only; a HIT is counted
        by :meth:`load_executable` once an executable is actually
        served — a verified blob that later fails to deserialize must
        end up in the miss column, not the hit column."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                header = json.loads(f.readline())
                payload = f.read()
        except (OSError, ValueError):
            _MISSES.inc()
            return None, {}
        try:
            ok = (header.get("fingerprint") == _jax_fingerprint()
                  and header.get("size") == len(payload)
                  and header.get("sha256") ==
                  hashlib.sha256(payload).hexdigest())
        except Exception:
            ok = False
        if not ok:
            _CORRUPT.inc()
            _MISSES.inc()
            self.logger.warning(
                "compile cache entry %s failed verification; treating "
                "as a miss (it will be re-traced and overwritten)", path)
            return None, {}
        meta = header.get("meta")
        return payload, (meta if isinstance(meta, dict) else {})

    def get_bytes(self, key):
        """The verified payload for ``key``, or None (miss) — see
        :meth:`get_entry` for the failure-mode contract."""
        return self.get_entry(key)[0]

    def put_bytes(self, key, payload, meta=None):
        """Atomically publish ``payload`` under ``key`` (tmp + fsync +
        rename — concurrent writers converge on identical files, a
        crash mid-write leaves no visible entry)."""
        os.makedirs(self.directory, exist_ok=True)
        header = {"sha256": hashlib.sha256(payload).hexdigest(),
                  "size": len(payload),
                  "fingerprint": _jax_fingerprint(),
                  "time": time.time()}
        if meta:
            header["meta"] = meta
        fd, tmp = tempfile.mkstemp(dir=self.directory, prefix=".tmp_cc_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(json.dumps(header).encode() + b"\n")
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(key))
        except OSError as e:
            # a full/read-only disk must not kill training — the cache
            # is an accelerator, not a dependency
            self.logger.warning("compile cache store failed: %r", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        _STORES.inc()
        return True

    # -- executables -------------------------------------------------------
    def load_executable_entry(self, key):
        """``(callable, meta)`` — :meth:`load_executable` plus the
        entry's meta sidecar (``{"flops": ...}`` when the storer
        recorded its compile-time cost analysis, so a warm start keeps
        the online MFU gauge fed without a fresh compile to ask).
        ``(None, {})`` on any miss."""
        blob, meta = self.get_entry(key)
        if blob is None:
            return None, {}
        fn = self._deserialize(key, blob)
        return fn, (meta if fn is not None else {})

    def load_executable(self, key):
        """Deserialize the cached executable for ``key`` into a
        callable (``jax.jit`` of the exported artifact's call — fast
        steady-state dispatch, NO trace of the original Python).  Any
        deserialization failure is a silent miss: jax.export artifacts
        embed their own compatibility checks, and an incompatible one
        must degrade to a fresh trace, not a crash."""
        blob = self.get_bytes(key)
        if blob is None:
            return None
        return self._deserialize(key, blob)

    def _deserialize(self, key, blob):
        try:
            import jax
            from jax import export as _export

            exported = _export.deserialize(blob)
            fn = jax.jit(exported.call)
        except Exception as e:
            # byte-verified but undeserializable: a MISS (the consumer
            # traces fresh), counted as such — hits must only ever mean
            # "a trace+compile did not happen"
            _CORRUPT.inc()
            _MISSES.inc()
            self.logger.warning(
                "compile cache entry %s verified but failed to rebuild "
                "an executable (%r); treating as a miss",
                self._path(key), e)
            return None
        _HITS.inc()
        return fn

    def store_executable(self, key, jit_fn, *avals, meta=None, **kw_avals):
        """Serialize ``jit_fn`` lowered at ``avals`` and publish it
        under ``key``.  ``meta`` (JSON-able dict — e.g. the executable's
        cost-analysis FLOPs) rides the entry header and comes back from
        :meth:`load_executable_entry`.  The export re-traces the
        function once (cold path, already paying a trace) — never
        raises: an unexportable program (unsupported primitive,
        platform quirk) just leaves the cache cold."""
        try:
            from jax import export as _export

            exported = _export.export(jit_fn)(*avals, **kw_avals)
            return self.put_bytes(key, exported.serialize(), meta=meta)
        except Exception as e:
            self.logger.warning(
                "compile cache: could not export executable for key "
                "%s... (%r); entry skipped", key[:12], e)
            return False

    def stats(self):
        """Entry count + bytes on disk (observability helper)."""
        n, total = 0, 0
        try:
            for name in os.listdir(self.directory):
                if name.endswith(".exe"):
                    n += 1
                    total += os.path.getsize(
                        os.path.join(self.directory, name))
        except OSError:
            pass
        return {"entries": n, "bytes": total, "directory": self.directory}
