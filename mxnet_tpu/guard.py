"""Numerical-integrity guard: SDC sentinels, mesh-agreed step skip/rewind,
and cross-rank gradient voting.

Every robustness layer so far (fault seams, preemption lifecycle, elastic
resharding, flight recorder, fleet failover) defends against *process*
failures — crashes, hangs, preemptions.  This module defends against
*wrong values*: a NaN gradient outside the AMP path, a loss spike from a
poisoned batch, or a degrading chip silently corrupting math mid-run —
the silent-data-corruption class that at pod scale burns days of goodput
undetected (PAPERS.md: large-run postmortems consistently report SDC as
the failure mode checkpoint/restart machinery never notices).

Three tiers, each a generalization of machinery already in-tree:

**Sentinels** — :meth:`Guard.check` generalizes the AMP
``LossScaler.has_overflow`` fused reduction (PR 5) into ONE
lazily-dispatched per-step integrity vector: non-finite gradient count +
global gradient norm + loss value, summed as device ops with a single
blocking host sync for all of them, AMP or not.  The host-side values are
classified against a trailing robust window (median/MAD — a spike cannot
poison the baseline that detects it) into one of the verdicts

    ``ok`` | ``nonfinite`` | ``loss_spike`` | ``grad_anomaly``

Multi-process, the local sentinel contributions are summed through ONE
``allreduce_hosts`` collective (the ``check_stop`` agreement shape:
issued unconditionally on every peer, strided by
``MXNET_GUARD_SYNC_EVERY`` with off-cycle calls returning the last
AGREED verdict), so every rank classifies the *same* global vector and
acts on the SAME step — equal-call-count contract preserved by
construction.

**Remediation ladder** (knob-driven, ``MXNET_GUARD_*``):

    verdict != ok
        └─ skip-step          zero the update (the AMP overflow-skip
           (MXNET_GUARD_SKIP)  semantics, generalized): the anomalous
            │                  gradients are simply never applied
            └─ rewind          after MXNET_GUARD_REWIND_AFTER anomalies
               (bound manager)  inside the window: restore
                │               ``latest_valid_step()`` + bit-exact
                │               ``train_state`` resume (PR 5), charged
                │               to the ``rewind`` goodput bucket
                └─ quarantine   per-bucket checksums + canary vote
                   (below)      name the corrupt RANK; run_with_recovery
                                escalates to a reshard-to-survivors
                                restart

**Quarantine / cross-rank voting** — post-allreduce flat gradient
buckets are bit-identical on every rank *by construction* (the reduced
payload is the same array everywhere), so a per-bucket checksum
(``MXNET_GUARD_CHECKSUM=1``, stamped into the flight-recorder ring via
:func:`stamp_bucket_checksum`) that differs across ranks is proof of
SDC or desync on a specific rank at a specific step —
``telemetry_agg.merge_blackboxes`` compares the stamped digests offline
and emits a ``numerical_divergence`` verdict naming the minority rank
(``teldump blame``).  Independently, :meth:`Guard.canary` recomputes a
caller-provided deterministic microbatch every
``MXNET_GUARD_CANARY_EVERY`` steps and votes the digests across ranks
ONLINE (one-hot slot gather in a single collective): a minority digest
raises :class:`NumericalDivergence` on every rank uniformly, which
``checkpoint.run_with_recovery`` treats as a rewind-class failure
(downtime charged to ``rewind``, black box dumped with the divergence
reason) and — with a ``resharder`` bound — restarts onto the surviving
ranks.

Wiring: :func:`attach` wraps ``Trainer.step`` with the verdict gate
(composes with ``amp.init_trainer`` — attach AFTER amp, and the AMP
overflow skip then routes through the guard's single fused sync, so a
guarded AMP step still pays exactly ONE host sync total);
``TrainStep.run(guard=...)`` polls the loss sentinel on the fused jit
path.  Fault seams: ``guard.check`` / ``guard.rewind`` /
``guard.canary``.  The ``guard-discipline`` static pass (MXT120/121)
enforces that verdict collectives stay call-count-uniform and that no
optimizer/parameter mutation bypasses the verdict gate in guarded
scopes.
"""
from __future__ import annotations

import collections
import logging
import time
import zlib

import numpy as np

from . import env as _env
from . import fault as _fault
from . import telemetry as _telemetry
from .base import MXNetError

__all__ = ["Guard", "NumericalDivergence", "GuardRewind", "VERDICTS",
           "attach", "nonfinite_total", "integrity_stats",
           "checksum_enabled", "stamp_bucket_checksum", "enabled"]

_LOGGER = logging.getLogger(__name__)

VERDICTS = ("ok", "nonfinite", "loss_spike", "grad_anomaly")

# steps of clean history before the robust window can call a spike —
# below this the guard only trips on hard non-finite evidence
MIN_HISTORY = 8

_CHECKS_TOTAL = _telemetry.counter(
    "mxnet_guard_checks_total",
    "fused integrity-sentinel checks issued (one host sync each on "
    "sync-stride cycles)")
_VERDICTS_TOTAL = _telemetry.counter(
    "mxnet_guard_verdicts_total",
    "agreed non-ok integrity verdicts by class",
    labelnames=("verdict",))
_SKIPS_TOTAL = _telemetry.counter(
    "mxnet_guard_skips_total",
    "optimizer steps skipped (update zeroed) on an anomalous verdict")
_REWINDS_TOTAL = _telemetry.counter(
    "mxnet_guard_rewinds_total",
    "rewinds to the latest valid checkpoint after repeated anomalies")
_CHECKSUMS_TOTAL = _telemetry.counter(
    "mxnet_guard_bucket_checksums_total",
    "post-allreduce per-bucket checksum stamps written to the flight "
    "recorder (quarantine mode)")
_CANARY_TOTAL = _telemetry.counter(
    "mxnet_guard_canary_votes_total",
    "deterministic canary-microbatch recompute votes taken")


class NumericalDivergence(MXNetError):
    """A rank's recomputed values diverge from the mesh majority —
    silent data corruption localized to specific rank(s).  Raised
    UNIFORMLY on every rank (the vote is a single agreed collective),
    so ``run_with_recovery`` restarts the whole job together; with a
    ``resharder`` bound the restart reshards to the survivors."""

    def __init__(self, message, ranks=()):
        super().__init__(message)
        self.ranks = tuple(sorted(int(r) for r in ranks))


class GuardRewind(MXNetError):
    """Escalation from a guarded loop that cannot rewind in place (the
    fused ``TrainStep`` path commits donated buffers before the verdict
    lands): ``run_with_recovery`` absorbs it as a rewind-class restart
    from the latest valid checkpoint."""


def enabled():
    """The master gate (``MXNET_GUARD``)."""
    return _env.guard_enabled()


def checksum_enabled():
    """Quarantine-tier per-bucket checksum stamps (``MXNET_GUARD_CHECKSUM``).

    Deliberately independent of the master gate so an operator can turn
    ON evidence collection for a suspected-SDC job without changing its
    step semantics."""
    return _env.guard_checksum()


# --------------------------------------------------------------------------
# fused device-side sentinel reductions (lazily dispatched, NO host sync)
# --------------------------------------------------------------------------
def nonfinite_total(params):
    """Fused non-finite count over every float gradient of ``params``
    as ONE lazily-dispatched device scalar (float32), or None when no
    float gradients exist.  This is the PR 5 ``LossScaler.has_overflow``
    reduction, extracted so AMP and the guard share one source: sums of
    non-negative counts keep the ``> 0`` verdict exact under float32
    accumulation, and nothing here blocks — the caller decides where the
    single host sync happens."""
    import jax.numpy as jnp

    total = None
    for p in params:
        if p.grad_req == "null" or p._data is None:
            continue
        for g in p.list_grad():
            v = g._get()
            if not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            bad = jnp.sum(~jnp.isfinite(v)).astype(jnp.float32)
            total = bad if total is None else total + bad
    return total


def integrity_stats(params=None, loss=None):
    """The per-step integrity vector as ONE lazily-dispatched device
    array ``[nonfinite_count, grad_sq_norm, loss, loss_present]``
    (float32).  Non-finite gradient elements are zeroed inside the norm
    reduction so the norm channel stays finite (the count channel
    already carries the non-finite evidence); ``loss_present`` lets a
    multi-process sum recover the mean loss without a second
    collective."""
    import jax.numpy as jnp

    nf = jnp.float32(0.0)
    gsq = jnp.float32(0.0)
    if params is not None:
        for p in params:
            if p.grad_req == "null" or p._data is None:
                continue
            for g in p.list_grad():
                v = g._get()
                if not jnp.issubdtype(v.dtype, jnp.floating):
                    continue
                fin = jnp.isfinite(v)
                nf = nf + jnp.sum(~fin).astype(jnp.float32)
                safe = jnp.where(fin, v, 0).astype(jnp.float32)
                gsq = gsq + jnp.sum(safe * safe)
    if loss is not None:
        raw = getattr(loss, "_get", None)
        lv = raw() if callable(raw) else loss
        lv = jnp.mean(jnp.asarray(lv).astype(jnp.float32))
        has = jnp.float32(1.0)
    else:
        lv = jnp.float32(0.0)
        has = jnp.float32(0.0)
    return jnp.stack([nf, gsq, lv, has])


def _robust_spike(value, history, threshold):
    """One-sided robust z-test: is ``value`` above the window median by
    more than ``threshold`` robust deviations?  Scale is the MAD
    (consistency factor 1.4826) floored at 1e-3·max(1, |median|) so a
    perfectly flat window cannot make every epsilon a spike.  Pure and
    deterministic — identical history + value on every rank means an
    identical verdict on every rank."""
    if len(history) < MIN_HISTORY or threshold <= 0:
        return False
    med = float(np.median(history))
    mad = float(np.median([abs(v - med) for v in history]))
    scale = max(1.4826 * mad, 1e-3 * max(1.0, abs(med)))
    return (value - med) > threshold * scale


class Guard:
    """The per-run integrity plane: fused sentinel check + trailing
    robust window + the skip/rewind remediation ladder.

    One instance per training loop (``attach`` hangs it off the Trainer
    as ``trainer._guard``).  All thresholds default from the
    ``MXNET_GUARD_*`` knobs; constructor arguments override for tests.
    ``_testing_force`` routes the agreement collective through the real
    combine path on a single process (the ``allreduce_hosts`` testing
    convention)."""

    def __init__(self, window=None, loss_spike=None, grad_spike=None,
                 skip=None, rewind_after=None, sync_every=None,
                 _testing_force=False):
        self._window = window if window is not None \
            else _env.guard_window()
        self._loss_spike = loss_spike if loss_spike is not None \
            else _env.guard_loss_spike()
        self._grad_spike = grad_spike if grad_spike is not None \
            else _env.guard_grad_spike()
        self._skip = skip if skip is not None else _env.guard_skip()
        self._rewind_after = rewind_after if rewind_after is not None \
            else _env.guard_rewind_after()
        self._sync_every = max(1, sync_every if sync_every is not None
                               else _env.guard_sync_every())
        self._testing_force = _testing_force
        self._losses = collections.deque(maxlen=self._window)
        self._norms = collections.deque(maxlen=self._window)
        self._recent = collections.deque(maxlen=self._window)
        self._calls = 0
        self._agreed = "ok"
        self.last_stats = {"nonfinite": 0.0, "grad_norm": 0.0,
                           "loss": None}
        # rewind binding (all optional; unbound => the ladder tops out
        # at skip, with a once-per-run warning)
        self._manager = None
        self._net = None
        self._trainer = None
        self._dataloader = None
        self._scaler = None
        self._rewind_warned = False

    # -- rewind binding ----------------------------------------------------
    def bind_rewind(self, manager, net=None, trainer=None,
                    dataloader=None, scaler=None):
        """Arm the rewind tier: ``manager`` is a ``CheckpointManager``
        (its ``latest_valid_step``/``restore``/``read_train_state`` are
        the PR 5 bit-exact resume machinery); net/trainer/dataloader/
        scaler are re-wound in place when provided."""
        self._manager = manager
        self._net = net
        self._trainer = trainer
        self._dataloader = dataloader
        self._scaler = scaler
        return self

    # -- the fused sentinel check -----------------------------------------
    def check(self, params=None, loss=None):
        """ONE integrity check: fused device reduction, one agreement
        collective, one host sync — classified into a verdict every
        rank shares.

        Called unconditionally at every guarded step boundary (the
        equal-call-count contract; MXT121 flags rank-conditional call
        sites).  Off-stride calls (``MXNET_GUARD_SYNC_EVERY`` > 1)
        issue NO collective and NO sync and return the last AGREED
        verdict — exactly ``lifecycle.check_stop``'s amortization
        shape, so anomaly latency grows to at most N steps."""
        _fault.check("guard.check")
        _CHECKS_TOTAL.inc()
        self._calls += 1
        if self._calls % self._sync_every != 0:
            # off-cycle: every peer takes this branch at the same call
            # count, so collective counts stay uniform
            # mxtpu: noqa[MXT003] stride is call-count-deterministic and
            # identical on every peer (check_stop's amortization shape)
            return self._agreed
        import jax

        stats = integrity_stats(params, loss)
        if jax.process_count() > 1 or self._testing_force:
            from .parallel.collectives import allreduce_hosts

            # the agreement: local sentinel contributions sum into one
            # global vector, so every rank classifies identical values
            stats = allreduce_hosts(stats,
                                    _testing_force=self._testing_force)
        # THE one designed host sync of a guarded step — the fused
        # sentinel vector crosses to the host exactly once here
        # mxtpu: noqa[MXT010]
        vec = np.asarray(stats)
        verdict = self._classify(float(vec[0]), float(vec[1]),
                                 float(vec[2]), float(vec[3]))
        self._agreed = verdict
        if verdict != "ok":
            _VERDICTS_TOTAL.labels(verdict=verdict).inc()
            self._flight_note("guard_verdict", verdict=verdict,
                              nonfinite=self.last_stats["nonfinite"],
                              grad_norm=self.last_stats["grad_norm"],
                              loss=self.last_stats["loss"])
            _LOGGER.warning(
                "guard verdict %s (nonfinite=%.0f grad_norm=%.4g "
                "loss=%s)", verdict, self.last_stats["nonfinite"],
                self.last_stats["grad_norm"], self.last_stats["loss"])
        return verdict

    def _classify(self, nf, gsq, loss_sum, loss_n):
        """Host-side classification of the agreed global vector against
        the trailing robust window.  Pure: identical inputs + window
        state give the identical verdict on every rank (the window is
        fed only by agreed values, so it stays identical too)."""
        loss = (loss_sum / loss_n) if loss_n > 0 else None
        norm = float(np.sqrt(max(gsq, 0.0)))
        self.last_stats = {"nonfinite": nf, "grad_norm": norm,
                           "loss": loss}
        if nf > 0 or (loss is not None and not np.isfinite(loss)) \
                or not np.isfinite(norm):
            verdict = "nonfinite"
        elif loss is not None and _robust_spike(loss, self._losses,
                                                self._loss_spike):
            verdict = "loss_spike"
        elif gsq > 0 and _robust_spike(norm, self._norms,
                                       self._grad_spike):
            verdict = "grad_anomaly"
        else:
            verdict = "ok"
        if verdict == "ok":
            # only clean steps feed the baseline: a burst of anomalies
            # cannot drag the median toward itself
            if loss is not None:
                self._losses.append(loss)
            if gsq > 0:
                self._norms.append(norm)
        self._recent.append(0 if verdict == "ok" else 1)
        return verdict

    # -- the remediation ladder -------------------------------------------
    def action(self, verdict):
        """Map an agreed verdict to ``commit`` | ``skip`` | ``rewind``.
        Deterministic in (verdict, window state, knobs) — all agreed or
        rank-uniform — so every rank takes the same action at the same
        step."""
        if verdict == "ok":
            return "commit"
        if self._rewind_after > 0 and \
                sum(self._recent) >= self._rewind_after:
            if self._manager is not None:
                return "rewind"
            if not self._rewind_warned:
                self._rewind_warned = True
                _LOGGER.warning(
                    "guard: %d anomalies in the window but no "
                    "CheckpointManager bound (Guard.bind_rewind) — "
                    "staying at skip", sum(self._recent))
        return "skip" if self._skip else "commit"

    def note_skip(self, verdict):
        """Account one zeroed update (telemetry + flight event)."""
        _SKIPS_TOTAL.inc()
        self._flight_note("guard_skip", verdict=verdict)

    def rewind(self):
        """Drop back to the newest VALID checkpoint and re-apply its
        exact train state (RNG, dataloader position, loss scale) —
        PR 5's bit-exact resume, triggered by values instead of a
        crash.  Returns the step rewound to (None when no valid
        checkpoint exists — the caller falls back to skip).  Wall time
        is charged to the ``rewind`` goodput bucket."""
        _fault.check("guard.rewind")
        if self._manager is None:
            return None
        t0 = time.perf_counter()
        step = self._manager.latest_valid_step()
        if step is None:
            _LOGGER.warning("guard: rewind requested but no valid "
                            "checkpoint exists — skipping instead")
            return None
        self._manager.restore(self._net, self._trainer, step=step)
        ts = self._manager.read_train_state(step)
        if ts:
            from . import lifecycle as _lifecycle

            _lifecycle.restore_train_state(ts, self._dataloader,
                                           self._scaler)
            if ts.get("guard") is not None:
                self.load_state_dict(ts["guard"])
        # the anomalous episode is over: restart the ladder so the
        # resumed trajectory gets a fresh window (a stale anomaly count
        # would re-trip the rewind on its first wobble)
        self._recent.clear()
        self._losses.clear()
        self._norms.clear()
        self._agreed = "ok"
        _REWINDS_TOTAL.inc()
        dt = time.perf_counter() - t0
        _telemetry.goodput_note("rewind", dt)
        self._flight_note("guard_rewind", step=int(step),
                          seconds=round(dt, 6))
        _LOGGER.warning("guard: rewound to step %d after repeated "
                        "anomalies (%.3fs)", step, dt)
        return step

    # -- quarantine: canary recompute + cross-rank vote --------------------
    def canary(self, fn, step=None):
        """Recompute a caller-provided DETERMINISTIC microbatch and vote
        the result digest across ranks.  ``fn()`` must be pure and
        identical on every rank (fixed inputs, fixed params — e.g. a
        forward pass over a frozen canary batch): its output is
        bit-identical across ranks unless a rank's hardware corrupts
        the math.  One collective (one-hot digest-slot gather), one
        host sync; a minority digest raises
        :class:`NumericalDivergence` on EVERY rank uniformly, naming
        the minority.  Returns this rank's digest."""
        _fault.check("guard.canary")
        _CANARY_TOTAL.inc()
        import jax

        out = fn()
        raw = getattr(out, "_get", lambda: out)()
        # the digest must cover the recomputed bytes on host; the canary
        # is stride-gated OFF the hot path — mxtpu: noqa[MXT010]
        arr = np.asarray(raw)
        # 24-bit digest: exactly representable in float32, so the
        # one-hot slot gather below is lossless
        digest = zlib.crc32(np.ascontiguousarray(arr).tobytes()) \
            & 0xFFFFFF
        self._flight_note("guard_canary", step=step, digest=int(digest))
        nproc = jax.process_count()
        if nproc <= 1 and not self._testing_force:
            return int(digest)
        from .parallel.collectives import allreduce_hosts

        import jax.numpy as jnp

        rank = jax.process_index()
        world = max(nproc, 1)
        slots = jnp.zeros((world,), jnp.float32).at[rank].set(
            float(digest))
        gathered = allreduce_hosts(slots,
                                   _testing_force=self._testing_force)
        # one host sync; every rank sees the identical digest table, so
        # the vote below is agreed by construction
        # mxtpu: noqa[MXT010]
        table = [int(d) for d in np.asarray(gathered)]
        counts = collections.Counter(table)
        majority = max(sorted(counts), key=lambda d: counts[d])
        minority = sorted(r for r, d in enumerate(table)
                          if d != majority)
        if minority and len(set(counts.values())) > 1:
            self._flight_note("guard_canary_divergence",
                              step=step, ranks=minority,
                              digests=table)
            raise NumericalDivergence(
                f"canary recompute diverged: rank(s) {minority} "
                f"disagree with the {counts[majority]}-rank majority "
                f"digest {majority:#08x} at step {step} — silent data "
                "corruption on the minority rank(s)", ranks=minority)
        return int(digest)

    # -- exact-resume state -------------------------------------------------
    def state_dict(self):
        """Window + ladder state for bit-exact resume: a resumed run
        classifies its next step exactly as the original would have
        (``lifecycle.capture_train_state(guard=...)``)."""
        return {"losses": [float(v) for v in self._losses],
                "norms": [float(v) for v in self._norms],
                "recent": [int(v) for v in self._recent],
                "calls": int(self._calls),
                "agreed": str(self._agreed)}

    def load_state_dict(self, state):
        self._losses.clear()
        self._losses.extend(float(v) for v in state.get("losses", ()))
        self._norms.clear()
        self._norms.extend(float(v) for v in state.get("norms", ()))
        self._recent.clear()
        self._recent.extend(int(v) for v in state.get("recent", ()))
        self._calls = int(state.get("calls", 0))
        self._agreed = str(state.get("agreed", "ok"))

    # -- the fused-path (TrainStep) sentinel --------------------------------
    def poll_loss(self, loss, step=None):
        """Loss-only sentinel for the fused jit path, where gradients
        never surface and the update is committed (donated buffers)
        before any verdict can land: a skip is impossible, so the
        ladder escalates straight to :class:`GuardRewind` — absorbed by
        ``run_with_recovery`` as a rewind-class restart from the latest
        valid checkpoint.  Returns the verdict."""
        verdict = self.check(loss=loss)
        if verdict == "ok":
            return verdict
        if self.action(verdict) == "rewind" or (
                self._rewind_after > 0
                and sum(self._recent) >= self._rewind_after):
            self._recent.clear()
            raise GuardRewind(
                f"guard verdict {verdict!r} persisted for "
                f"{self._rewind_after} steps on the fused path at "
                f"step {step} — escalating to a checkpoint rewind")
        self.note_skip(verdict)
        return verdict

    @staticmethod
    def _flight_note(kind, **fields):
        """Context event into the flight-recorder ring — lazy and
        failure-tolerant (telemetry's ``_flight_note`` shape)."""
        try:
            from . import flight_recorder as _flight

            clean = {k: v for k, v in fields.items() if v is not None}
            _flight.record_event(kind, **clean)
        except Exception:
            pass


# --------------------------------------------------------------------------
# quarantine: post-allreduce per-bucket checksum stamps
# --------------------------------------------------------------------------
def stamp_bucket_checksum(key, flat, step=None):
    """Stamp the checksum of a post-allreduce flat bucket into the
    flight-recorder ring (quarantine tier, ``MXNET_GUARD_CHECKSUM=1``).

    The reduced flat payload is bit-identical on every rank BY
    CONSTRUCTION (same collective, same inputs), so differing digests
    at the same (step, key) across the merged black-box rings are
    positive evidence of SDC/desync on specific rank(s) —
    ``merge_blackboxes`` turns them into a ``numerical_divergence``
    verdict naming the minority.  The sync below is the quarantine
    tier's deliberate evidence-collection cost, gated off the default
    path by the knob.
    """
    try:
        from . import flight_recorder as _flight

        # quarantine-only blocking readback: the digest must cover the
        # exact bytes every rank holds — mxtpu: noqa[MXT010]
        payload = np.ascontiguousarray(np.asarray(flat))
        crc = zlib.crc32(payload.tobytes()) & 0xFFFFFFFF
        _CHECKSUMS_TOTAL.inc()
        fields = {"key": str(key), "crc": int(crc),
                  "seq": _flight.position()}
        if step is not None:
            fields["step"] = int(step)
        _flight.record_event("guard_checksum", **fields)
    except Exception:
        # evidence collection must never take down the step loop
        _LOGGER.debug("guard checksum stamp failed", exc_info=True)


# --------------------------------------------------------------------------
# the Trainer verdict gate
# --------------------------------------------------------------------------
def attach(trainer, guard=None, manager=None, net=None, dataloader=None):
    """Wrap ``trainer.step`` with the guard verdict gate.

    Composes with AMP: call AFTER ``amp.init_trainer`` and the guarded
    step REPLACES the AMP wrapper's separate ``has_overflow`` sync —
    the fused sentinel's non-finite channel feeds
    ``LossScaler.update_scale`` directly, so a guarded AMP step pays
    exactly ONE host sync total and the overflow verdict is identical
    to the standalone scaler's (the parity test pins this).

    Per step: ``check`` → ``action`` → commit (the original step) /
    skip (update zeroed, counted) / rewind (bound via ``manager``).
    The staged loss for the loss-spike sentinel is fed with
    ``trainer._guard.observe_loss(loss)`` — optional; without it the
    loss channel is simply absent.  Returns ``trainer``."""
    g = guard if guard is not None else Guard()
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if manager is not None:
        g.bind_rewind(manager, net=net, trainer=trainer,
                      dataloader=dataloader, scaler=scaler)
    # the PLAIN class step, even when amp.init_trainer already replaced
    # the instance attribute: the unified gate below owns both the
    # verdict and the loss-scale bookkeeping the AMP wrapper did
    plain_step = type(trainer).step.__get__(trainer)
    g._staged_loss = None

    def observe_loss(loss):
        g._staged_loss = loss

    def guarded_step(batch_size, ignore_stale_grad=False):
        staged, g._staged_loss = g._staged_loss, None
        verdict = g.check(trainer._params, loss=staged)
        act = g.action(verdict)
        if act == "rewind":
            if g.rewind() is None:
                act = "skip"
        if verdict == "ok":
            if scaler is not None:
                eff = 1.0 if trainer._amp_unscaled \
                    else scaler.loss_scale
                trainer._scale = trainer._amp_original_scale / eff
                plain_step(batch_size,
                           ignore_stale_grad=ignore_stale_grad)
                trainer._scale = trainer._amp_original_scale
            else:
                plain_step(batch_size,
                           ignore_stale_grad=ignore_stale_grad)
        elif act == "skip":
            g.note_skip(verdict)
        if scaler is not None:
            trainer._amp_unscaled = False
            # the agreed non-finite channel IS the overflow verdict —
            # no second has_overflow sync
            scaler.update_scale(g.last_stats["nonfinite"] > 0)

    trainer._guard = g
    g.observe_loss = observe_loss
    trainer.step = guarded_step
    return trainer
