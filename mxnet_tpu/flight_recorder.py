"""Distributed flight recorder: a per-rank collective ledger with
black-box crash dumps.

The introspection plane (PR 14) answers "how is the job doing"; this
module answers the question the watchdog cannot: **which collective is
the mesh wedged in, and which rank fell out of program order**.  XLA
collectives rendezvous by issue order (see parallel/collectives.py's
equal-call-count contract), so when rank N stalls, the only artifact
that localizes the hang is a per-rank ledger of what was issued — and
it has to already exist when the job dies.

Design (all host-side, zero device work, zero host syncs):

- **Always-on preallocated ring** (``MXNET_FLIGHT_RECORDER``, default
  on; ``MXNET_FLIGHT_RECORDER_CAP`` slots, default 4096).  Recording is
  one short lock section + one dict build; the per-op eager dispatch
  path and the serving decode loop never touch it.
- **Collective ledger**: every Python-level collective issue site
  (:func:`collective` context manager) stamps an entry carrying a
  **monotonic per-rank sequence number** and a digest-stable *tag* of
  ``(op, shape, dtype, axis, bucket-generation)``.  Entry and exit are
  separate ``perf_counter`` stamps, so a rank wedged *inside* a
  blocking collective is distinguishable from one that stopped
  *between* collectives.  Because every SPMD peer issues the same
  collectives in the same order, equal sequence numbers across ranks
  must carry equal tags — the alignment key
  :func:`~mxnet_tpu.telemetry_agg.merge_blackboxes` blames by.
  ``mxnet_collective_ledger_position`` exports the live position, so
  cross-rank ledger skew is visible in the telemetry aggregation
  *before* a hang.
- **Context events** ride the same ring: step boundaries
  (telemetry.step_begin/step_end), fault-seam trips, compile events,
  lifecycle transitions (stop requests, restarts, SLO breaches), and
  the numerical-integrity guard's evidence stamps
  (``guard_checksum`` post-allreduce bucket digests, ``guard_canary``
  recompute digests, verdicts/skips/rewinds — mxnet_tpu/guard.py;
  the digests are what ``merge_blackboxes`` turns into a
  ``numerical_divergence`` blame verdict) — the "what was the job
  doing" context around the last collective.
- **Black-box dumps**: on any abnormal exit (watchdog stall,
  ``run_with_recovery`` failure, forced grace-deadline exit, unhandled
  exception in the TrainStep/serving loops) each rank atomically writes
  its ring as ``blackbox.rank<N>.json`` into the existing
  ``MXNET_TELEMETRY_AGG_DIR`` file gather (``MXNET_FLIGHT_DIR``
  overrides).  **Never a collective** — the mesh is presumed broken;
  each rank dumps alone and the merge happens offline
  (``tools/teldump blame``) or in the supervisor.

Exit-stamp semantics under async dispatch: jax dispatch is
asynchronous, so for jitted collective pairs the exit stamp marks
*dispatch* completion, not device completion — a rank wedged awaiting a
peer then parks *between* sequence numbers and the merge blames it as
"never entered seq N+1".  Host-blocking collectives (the host-value
allreduces, ``barrier``, ``fetch_global``) block inside the context, so
those wedge as "entered seq N but never exited".  Both shapes are
first-class blame verdicts.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time

from . import env as _env
from . import telemetry as _telemetry

__all__ = ["enabled", "configure", "collective", "record_event",
           "position", "snapshot_doc", "dump_blackbox", "reset",
           "BLACKBOX_PREFIX"]

BLACKBOX_PREFIX = "blackbox.rank"

_LOCK = threading.Lock()
_STATE = {
    "enabled": None,      # None = resolve from env on first use
    "cap": None,
    "rank": None,
    "world": None,
}
_RING: list = []          # preallocated slots, written at _POS % cap
_POS = [0]                # total events ever recorded
_SEQ = [0]                # collective sequence counter (monotonic)

_POSITION = _telemetry.gauge(
    "mxnet_collective_ledger_position",
    "monotonic per-rank collective sequence number (flight recorder); "
    "cross-rank skew of this gauge is a pre-hang signal")
_DUMPS = _telemetry.counter(
    "mxnet_flight_dumps_total",
    "black-box ring dumps written, by abnormal-exit reason",
    labelnames=("reason",))


def _ensure():
    """Resolve config lazily (lock held by callers or benign to race:
    worst case two threads build the same ring)."""
    if _STATE["enabled"] is None:
        with _LOCK:
            if _STATE["enabled"] is None:
                _STATE["cap"] = _env.flight_recorder_cap()
                _STATE["rank"] = _env.launcher_rank()
                _STATE["world"] = _env.launcher_world()
                del _RING[:]
                _RING.extend([None] * _STATE["cap"])
                # set "enabled" LAST: it is the lock-free fast-path gate
                _STATE["enabled"] = _env.flight_recorder_enabled()
    return _STATE["enabled"]


def enabled():
    """Whether the recorder is on (``MXNET_FLIGHT_RECORDER``, default
    1; resolved once — :func:`reset` re-reads the env)."""
    return _ensure()


def configure(enabled=None, capacity=None, rank=None, world=None):
    """Explicit (re)configuration — tests and embedders; production
    config comes from the env knobs.  Clears the ring."""
    with _LOCK:
        _STATE["enabled"] = _env.flight_recorder_enabled() \
            if enabled is None else bool(enabled)
        _STATE["cap"] = max(8, int(capacity)) if capacity is not None \
            else _env.flight_recorder_cap()
        _STATE["rank"] = _env.launcher_rank() if rank is None else int(rank)
        _STATE["world"] = _env.launcher_world() if world is None \
            else int(world)
        del _RING[:]
        _RING.extend([None] * _STATE["cap"])
        _POS[0] = 0
        _SEQ[0] = 0
    return dict(_STATE)


def reset():
    """Drop all state; next use re-resolves from the environment
    (test isolation, bench A/B arms)."""
    with _LOCK:
        _STATE.update(enabled=None, cap=None, rank=None, world=None)
        del _RING[:]
        _POS[0] = 0
        _SEQ[0] = 0


def _append_locked(entry):
    _RING[_POS[0] % _STATE["cap"]] = entry
    _POS[0] += 1


def record_event(kind, **fields):
    """Append one context event (``step`` / ``fault`` / ``compile`` /
    ``lifecycle`` / caller-defined) to the ring.  Disabled = one dict
    read."""
    if not _ensure():
        return
    entry = dict(fields)
    entry["kind"] = str(kind)
    entry["t"] = time.perf_counter()
    with _LOCK:
        _append_locked(entry)


def tag_of(op, shape=None, dtype=None, axis=None, generation=None):
    """The digest-stable collective tag: a readable string plus a short
    sha256 digest of the same fields — identical on every rank that
    issues the same collective (the merge's alignment invariant)."""
    parts = [str(op)]
    if shape is not None:
        parts.append("x".join(str(int(d)) for d in tuple(shape)))
    if dtype is not None:
        parts.append(str(dtype))
    if axis is not None:
        parts.append(str(axis))
    if generation is not None:
        parts.append(f"g{generation}")
    tag = ":".join(parts)
    digest = hashlib.sha256(tag.encode()).hexdigest()[:12]
    return tag, digest


class _Collective:
    """One stamped collective: enter allocates the sequence number and
    the ring entry; exit stamps completion (or the error)."""

    __slots__ = ("_entry",)

    def __init__(self, op, shape, dtype, axis, generation):
        tag, digest = tag_of(op, shape, dtype, axis, generation)
        entry = {"kind": "collective", "op": str(op), "tag": tag,
                 "digest": digest, "t0": time.perf_counter()}
        if generation is not None:
            entry["gen"] = str(generation)
        with _LOCK:
            _SEQ[0] += 1
            entry["seq"] = _SEQ[0]
            _append_locked(entry)
        self._entry = entry
        _POSITION.set(entry["seq"])

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # exit mutates the entry in place (if the ring wrapped past it
        # the dict is simply no longer referenced) — under _LOCK: the
        # watchdog thread's snapshot_doc may be copying this very dict
        # while the main thread exits a collective, and inserting a key
        # mid-iteration would raise, silently costing the black box
        t1 = time.perf_counter()
        err = repr(exc)[:200] if exc is not None else None
        with _LOCK:
            self._entry["t1"] = t1
            if err is not None:
                self._entry["error"] = err
        return False


class _NullCollective:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCollective()


def collective(op, shape=None, dtype=None, axis=None, generation=None):
    """Context manager stamping one collective issue: enter records the
    next per-rank sequence number + the tag digest, exit records
    completion.  Wrap the *Python issue point* — the call that
    dispatches the collective (see the module docstring for the async
    exit-stamp semantics).  Disabled = a shared no-op scope."""
    if not _ensure():
        return _NULL
    return _Collective(op, shape, dtype, axis, generation)


def position():
    """The current collective sequence number (0 before any stamp)."""
    return _SEQ[0]


def snapshot_doc():
    """The ring as a JSON-able document (in record order, oldest
    first): rank/world identity, ledger position, and every retained
    event.  Pure read — safe from any thread, including the watchdog's
    while the main thread is wedged."""
    _ensure()
    with _LOCK:
        pos, cap = _POS[0], _STATE["cap"]
        if pos <= cap:
            events = [dict(e) for e in _RING[:pos]]
        else:
            cut = pos % cap
            events = [dict(e) for e in _RING[cut:] + _RING[:cut]]
        return {
            "format": 1,
            "rank": _STATE["rank"],
            "world": _STATE["world"],
            "enabled": bool(_STATE["enabled"]),
            "capacity": cap,
            "events_recorded": pos,
            "position": _SEQ[0],
            "events": events,
        }


def _dump_dir(directory):
    if directory:
        return directory
    return _env.flight_dir()


def dump_blackbox(reason, directory=None):
    """Atomically write this rank's ring as ``blackbox.rank<N>.json``
    (tmp + rename — a reader never sees a torn file; the newest
    abnormal event wins).  Called on abnormal exits only; **never a
    collective** — each rank dumps alone, the merge happens offline.

    Returns the path, or None when the recorder is disabled or no dump
    directory is configured (``directory`` argument >
    ``MXNET_FLIGHT_DIR`` > ``MXNET_TELEMETRY_AGG_DIR``).  Never
    raises: the dump is the last act of a dying process and must not
    mask the original failure."""
    if not _ensure():
        return None
    directory = _dump_dir(directory)
    if not directory:
        return None
    try:
        doc = snapshot_doc()
        doc["reason"] = str(reason)
        doc["time"] = time.time()
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp_blackbox_")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, default=str)
            path = os.path.join(
                directory, f"{BLACKBOX_PREFIX}{doc['rank']}.json")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        return None
    _DUMPS.labels(reason=str(reason)).inc()
    return path
