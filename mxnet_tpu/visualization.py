"""Network visualization (reference: ``python/mxnet/visualization.py`` —
``plot_network`` + ``print_summary``, SURVEY.md §3.5 misc frontend).

This environment has no graphviz package/binary, so ``plot_network``
builds the DOT source itself and returns a tiny Digraph stand-in with the
same ``.source`` / ``.render()`` / ``.view()`` surface the reference's
graphviz object exposes; rendering to an image needs a ``dot`` binary at
the user's end.
"""
from __future__ import annotations

import subprocess

from .base import MXNetError

__all__ = ["plot_network", "print_summary"]

_NODE_STYLE = {
    "Convolution": ("box", "#fb8072"),
    "Deconvolution": ("box", "#fb8072"),
    "FullyConnected": ("box", "#fb8072"),
    "BatchNorm": ("box", "#bebada"),
    "LayerNorm": ("box", "#bebada"),
    "Activation": ("box", "#ffffb3"),
    "relu": ("box", "#ffffb3"),
    "Pooling": ("box", "#80b1d3"),
    "Flatten": ("box", "#fdb462"),
    "softmax": ("box", "#fccde5"),
    "null": ("oval", "#8dd3c7"),
}


class Digraph:
    """Minimal graphviz.Digraph-compatible holder for DOT source."""

    def __init__(self, source, name="plot"):
        self.source = source
        self.name = name

    def render(self, filename=None, format="dot", cleanup=False, view=False):
        filename = filename or self.name
        dot_path = f"{filename}.dot" if not filename.endswith(".dot") \
            else filename
        with open(dot_path, "w") as f:
            f.write(self.source)
        if format not in ("dot", None):
            try:
                out_path = f"{filename}.{format}"
                subprocess.run(["dot", f"-T{format}", dot_path,
                                "-o", out_path], check=True)
                return out_path
            except (FileNotFoundError, subprocess.CalledProcessError) as e:
                raise MXNetError(
                    f"rendering to {format!r} needs the graphviz 'dot' "
                    f"binary: {e}") from e
        return dot_path

    def view(self, *a, **k):  # pragma: no cover - no display here
        return self.render(*a, **k)

    def _repr_svg_(self):  # notebook convenience when dot exists
        try:
            out = subprocess.run(["dot", "-Tsvg"], input=self.source,
                                 capture_output=True, text=True, check=True)
            return out.stdout
        except Exception:
            return None


def _label(node):
    op = node.op or "null"
    a = node.attrs
    if op == "Convolution":
        return f"Convolution\\n{a.get('kernel')}/{a.get('stride')}, " \
               f"{a.get('num_filter')}"
    if op == "FullyConnected":
        return f"FullyConnected\\n{a.get('num_hidden')}"
    if op == "Pooling":
        return f"Pooling\\n{a.get('pool_type', 'max')}, {a.get('kernel')}"
    if op == "Activation":
        return f"Activation\\n{a.get('act_type')}"
    return op if op != "null" else node.name


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 hide_weights=True, save_format="dot"):
    """Build a DOT graph of the symbol (reference: mx.viz.plot_network)."""
    from .symbol.symbol import Symbol, _topo

    if not isinstance(symbol, Symbol):
        raise MXNetError("plot_network expects a Symbol (use "
                         "block._trace_to_symbol or sym API)")
    nodes = _topo(symbol._heads)
    nid = {id(n): i for i, n in enumerate(nodes)}
    lines = [f'digraph "{title}" {{',
             "  rankdir=BT;",
             '  node [fontsize=10, style=filled];']
    weight_like = set()
    if hide_weights:
        for n in nodes:
            if n.op is None and any(n.name.endswith(sfx) for sfx in
                                    ("weight", "bias", "gamma", "beta",
                                     "running_mean", "running_var",
                                     "moving_mean", "moving_var")):
                weight_like.add(id(n))
    for n in nodes:
        if id(n) in weight_like:
            continue
        op = n.op or "null"
        shape_style, color = _NODE_STYLE.get(op, ("box", "#d9d9d9"))
        lines.append(
            f'  n{nid[id(n)]} [label="{_label(n)}", shape={shape_style}, '
            f'fillcolor="{color}"];')
    for n in nodes:
        if id(n) in weight_like:
            continue
        for inp, _ in n.inputs:
            if id(inp) in weight_like:
                continue
            lines.append(f"  n{nid[id(inp)]} -> n{nid[id(n)]};")
    lines.append("}")
    return Digraph("\n".join(lines), name=title)


def print_summary(symbol, shape=None, line_length=88):
    """Per-layer text summary (reference: mx.viz.print_summary)."""
    from .symbol.symbol import Symbol, _topo

    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    nodes = _topo(symbol._heads)
    header = f"{'Layer (type)':<44}{'Inputs':>40}"
    out = ["_" * line_length, header, "=" * line_length]
    for n in nodes:
        if n.op is None:
            continue
        ins = ",".join(inp.name for inp, _ in n.inputs)
        out.append(f"{n.name + ' (' + n.op + ')':<44}{ins[:40]:>40}")
    out.append("=" * line_length)
    text = "\n".join(out)
    print(text)
    return text
