"""Legacy data iterators.

Reference: ``python/mxnet/io/io.py`` (DataIter, DataBatch, NDArrayIter,
ResizeIter, PrefetchingIter) over the C++ iterator registry in ``src/io/``
(SURVEY.md §3.4).  The C++ threaded parser→batcher→prefetcher pipeline is
replaced by the Gluon DataLoader's thread-pool prefetch; these classes keep
the Module-era API surface.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "ImageRecordIter", "CSVIter", "LibSVMIter",
           "MNISTIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{'_' + str(i) if i else ''}": d
                for i, d in enumerate(data)} if len(data) > 1 else \
            ({default_name: data[0]} if data else {})
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self.idx)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self.idx)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "roll_over":
            return self.cursor < self.num_data
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        out = []
        for _, v in arrays:
            end = self.cursor + self.batch_size
            ids = self.idx[self.cursor:min(end, self.num_data)]
            batch = v.asnumpy()[ids]
            if len(ids) < self.batch_size:  # pad
                pad = self.batch_size - len(ids)
                batch = _np.concatenate([batch, batch[:pad]])
            out.append(array(batch))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to size batches per epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetch wrapper (reference: mx.io.PrefetchingIter over
    dmlc::ThreadedIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import threading
        import queue

        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here supports one base iter")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._queue = queue.Queue(maxsize=2)
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            while True:
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batch)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        while self._thread.is_alive():
            try:
                if self._queue.get(timeout=0.1) is None:
                    break
            except Exception:
                break
        self.iter.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise NotImplementedError


class _DecodePipeline:
    """Decode/augment pool between the C++ byte reader and batching.

    Reference shape: ``src/io/iter_image_recordio_2.cc`` ParseChunk runs the
    decode stage on an OMP pool so a single Python thread never bounds
    throughput (SURVEY.md §4.5).  Here: a feeder thread pulls payload
    batches from the native reader, fans per-image decode out to a
    ThreadPoolExecutor (PIL/numpy release the GIL for the heavy parts), and
    queues assembled batches for ``next()``."""

    def __init__(self, reader, decode_method, n_threads, depth):
        import queue
        import threading
        import weakref
        from concurrent.futures import ThreadPoolExecutor

        self._reader = reader
        # weak binding: the running feeder thread must not keep an abandoned
        # iterator (and its reader/pool/buffers) alive forever
        self._decode = weakref.WeakMethod(decode_method)
        self._pool = ThreadPoolExecutor(max_workers=n_threads)
        self._q = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        idx = 0
        while not self._stop.is_set():
            decode = self._decode()  # WeakMethod: None once the owner died
            if decode is None:
                return
            try:
                payloads = self._reader.next_batch()
                if payloads is None:
                    self._put(None)
                    return
                futs = [self._pool.submit(decode, p, idx + i)
                        for i, p in enumerate(payloads)]
                idx += len(payloads)
                results = [f.result() for f in futs]
            except Exception as e:  # surface read/decode errors at next()
                self._put(e)
                return
            del decode
            if not self._put(results):
                return

    def _put(self, item):
        import queue

        # also abort when the owning iterator has been garbage-collected
        # (nobody will ever drain the queue)
        while not self._stop.is_set() and self._decode() is not None:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def get(self):
        # once terminal (exhausted or errored) the feeder is gone: replay
        # the terminal state instead of blocking on an empty queue forever
        done = getattr(self, "_done", None)
        if done is not None:
            if isinstance(done, Exception):
                raise MXNetError(
                    f"decode pipeline failed: {done!r}") from done
            return None
        item = self._q.get()
        if isinstance(item, Exception):
            self._done = item
            raise MXNetError(f"decode pipeline failed: {item!r}") from item
        if item is None:
            self._done = True
        return item

    def shutdown(self):
        import queue

        self._stop.set()
        while self._thread.is_alive():
            try:  # drain so a blocked _put can observe the stop flag
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        while True:  # discard whatever is left
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._pool.shutdown(wait=False)


class ImageRecordIter(DataIter):
    """Threaded image-record iterator (reference: src/io/iter_image_recordio_2.cc
    "ImageRecordIter" — shard reader → decode pool → batcher → prefetcher).

    TPU-native split: the C++ library (mxnet_tpu/native) owns file IO, record
    framing, num_parts/part_index sharding, epoch shuffling and prefetch;
    decode (PIL/numpy) and augmentation run on a thread pool here
    (``preprocess_threads``, ≙ the reference's OMP decode stage).  Supported
    record payloads: .npy-encoded arrays (recordio.pack_img default) and
    JPEG/PNG via PIL.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, resize=-1, num_parts=1, part_index=0, seed=0,
                 round_batch=True, prefetch_buffer=4, preprocess_threads=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        from .native import NativeRecordReader
        from . import recordio as _rio

        self._rio = _rio
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = _np.array([mean_r, mean_g, mean_b], dtype="float32")
        self.std = _np.array([std_r, std_g, std_b], dtype="float32")
        self.round_batch = round_batch
        self._seed = seed
        self._epoch = 0
        if preprocess_threads is None:
            from . import env as _env

            preprocess_threads = _env.cpu_worker_nthreads()
        self._n_threads = max(int(preprocess_threads), 1)
        self._depth = prefetch_buffer
        self._reader = NativeRecordReader(
            path_imgrec, batch_size, num_parts=num_parts,
            part_index=part_index, shuffle=shuffle, seed=seed,
            queue_depth=prefetch_buffer)
        self._data_name = data_name
        self._label_name = label_name
        self._pipeline = _DecodePipeline(self._reader, self._decode,
                                         self._n_threads, self._depth)

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        if self._pipeline is not None:  # may be closed / previously failed
            self._pipeline.shutdown()
        self._pipeline = None  # a failed reader.reset() must not leave a
        #                        dead pipeline that blocks next() forever
        self._reader.reset()
        self._epoch += 1
        self._exhausted = False
        self._pipeline = _DecodePipeline(self._reader, self._decode,
                                         self._n_threads, self._depth)

    def close(self):
        """Stop the decode pool deterministically (also runs when the
        iterator is garbage-collected via the pipeline's weak binding)."""
        if getattr(self, "_pipeline", None) is not None:
            self._pipeline.shutdown()
            self._pipeline = None

    def _decode(self, payload, index):
        # per-record RNG keyed by (seed, epoch, index): augmentation is
        # deterministic regardless of decode-thread scheduling
        rng = _np.random.RandomState(
            (self._seed * 1000003 + self._epoch * 7919 + index) % (2 ** 31))
        header, img = self._rio.unpack_img(payload)
        return self._augment(img, rng), header.label

    def _augment(self, img, rng):
        # img HWC uint8/float -> data_shape CHW float32
        c, h, w = self.data_shape
        if img.ndim == 2:
            img = img[:, :, None]
        # reconcile channel count with data_shape: gray->RGB replicate,
        # RGBA->drop alpha, RGB->gray luminance
        ic = img.shape[2]
        if ic != c:
            if ic == 1:
                img = _np.repeat(img, c, axis=2)
            elif ic == 4 and c == 3:
                img = img[:, :, :3]
            elif c == 1:
                img = img[:, :, :3].mean(axis=2, keepdims=True)
            else:
                raise MXNetError(
                    f"record has {ic} channels but data_shape wants {c}")
        if self.resize > 0:
            img = self._resize_short(img, self.resize)
        ih, iw = img.shape[:2]
        if self.rand_crop and ih >= h and iw >= w:
            y0 = rng.randint(0, ih - h + 1)
            x0 = rng.randint(0, iw - w + 1)
        else:
            y0 = max((ih - h) // 2, 0)
            x0 = max((iw - w) // 2, 0)
        img = img[y0:y0 + h, x0:x0 + w]
        if img.shape[0] != h or img.shape[1] != w:
            img = self._resize_exact(img, h, w)
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1]
        data = img.astype("float32")
        nch = data.shape[2]
        data = (data - self.mean[:nch]) / self.std[:nch]
        return _np.transpose(data, (2, 0, 1))

    @staticmethod
    def _resize_short(img, size):
        from PIL import Image

        ih, iw = img.shape[:2]
        scale = size / min(ih, iw)
        nh, nw = int(round(ih * scale)), int(round(iw * scale))
        return _np.asarray(Image.fromarray(img.astype("uint8")).resize(
            (nw, nh), Image.BILINEAR))

    @staticmethod
    def _resize_exact(img, h, w):
        from PIL import Image

        return _np.asarray(Image.fromarray(img.astype("uint8")).resize(
            (w, h), Image.BILINEAR))

    def next(self):
        from .ndarray import array as _array

        if getattr(self, "_exhausted", False):
            raise StopIteration
        if self._pipeline is None:
            raise MXNetError(
                "iterator is closed or a previous reset() failed; "
                "create a new ImageRecordIter")
        results = self._pipeline.get()
        if results is None:
            self._exhausted = True
            raise StopIteration
        imgs = [r[0] for r in results]
        labels = [r[1] for r in results]
        pad = self.batch_size - len(imgs)
        if pad > 0 and self.round_batch:
            # pad the tail batch with copies of the last record (reference
            # round_batch semantics); pad count lets callers mask them
            imgs.extend([imgs[-1]] * pad)
            labels.extend([labels[-1]] * pad)
        else:
            pad = 0
        data = _array(_np.stack(imgs))
        label = _array(_np.asarray(labels, dtype="float32"))
        return DataBatch(data=[data], label=[label], pad=pad)


class CSVIter(DataIter):
    """CSV file iterator (reference: ``src/io/iter_csv.cc`` CSVIter).

    Loads ``data_csv`` (and optional ``label_csv``) into host memory once
    (the reference streams chunk-wise; at the dataset sizes CSV is used for
    this is a simplification, not a constraint) and yields batch-size
    slices, each row reshaped to ``data_shape``.  ``round_batch`` pads the
    tail batch by wrapping to the head like the reference."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32",
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        self.round_batch = round_batch
        self._dtype = dtype
        self._data = _np.loadtxt(data_csv, delimiter=",",
                                 dtype=dtype, ndmin=2)
        n = self._data.shape[0]
        if self._data.shape[1] != int(_np.prod(self.data_shape)):
            raise MXNetError(
                f"csv row width {self._data.shape[1]} != data_shape "
                f"{self.data_shape}")
        self._data = self._data.reshape((n,) + self.data_shape)
        if label_csv is not None:
            self._label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype,
                                      ndmin=2).reshape((n,) + self.label_shape)
        else:
            self._label = _np.zeros((n,) + self.label_shape, dtype=dtype)
        self._data_name = data_name
        self._label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        # a (1,)-wide label squeezes to a vector (matching next())
        shape = (self.batch_size,) if self.label_shape == (1,) else \
            (self.batch_size,) + self.label_shape
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        self._cursor = 0

    def next(self):
        n = self._data.shape[0]
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        if end > n and not self.round_batch:
            raise StopIteration
        # modular take wraps however many times the pad requires
        ids = _np.arange(self._cursor, end) % n
        data = self._data[ids]
        label = self._label[ids]
        pad = end - n if end > n else 0
        self._cursor = end
        lbl = label[:, 0] if self.label_shape == (1,) else label
        return DataBatch(data=[array(data)], label=[array(lbl)], pad=pad)


class LibSVMIter(DataIter):
    """LibSVM-format iterator producing CSR batches (reference:
    ``src/io/iter_libsvm.cc`` LibSVMIter — the sparse input path for the
    factorization-machine / linear-model configs, SURVEY.md §3.4)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        if len(self.data_shape) != 1:
            raise MXNetError("LibSVMIter data_shape must be (num_features,)")
        self.round_batch = round_batch
        labels, indptr, indices, values = [], [0], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, _, v = tok.partition(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        self._labels = _np.asarray(labels, dtype="float32")
        self._indptr = _np.asarray(indptr, dtype="int64")
        self._indices = _np.asarray(indices, dtype="int64")
        self._values = _np.asarray(values, dtype="float32")
        if label_libsvm is not None:
            ext = _np.loadtxt(label_libsvm, dtype="float32", ndmin=1)
            ext = ext.reshape(-1)
            if ext.shape[0] != len(labels):
                raise MXNetError(
                    f"label file has {ext.shape[0]} rows but data file has "
                    f"{len(labels)}")
            self._labels = ext
        self._data_name = data_name
        self._label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name, (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def _rows_csr(self, row_ids):
        """Build a CSR batch from arbitrary row ids — stays sparse, so the
        tail-batch wrap never densifies a huge feature dim."""
        from .ndarray.sparse import CSRNDArray

        vals, inds, indptr = [], [], [0]
        for r in row_ids:
            lo, hi = self._indptr[r], self._indptr[r + 1]
            vals.append(self._values[lo:hi])
            inds.append(self._indices[lo:hi])
            indptr.append(indptr[-1] + (hi - lo))
        return CSRNDArray.create(
            _np.concatenate(vals) if vals else _np.zeros(0, "f"),
            _np.concatenate(inds) if inds else _np.zeros(0, "i8"),
            _np.asarray(indptr, dtype="int64"),
            (len(row_ids), self.data_shape[0]))

    def next(self):
        n = len(self._labels)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        if end > n and not self.round_batch:
            raise StopIteration
        ids = _np.arange(self._cursor, end) % n  # wraps any pad size
        csr = self._rows_csr(ids)
        label = self._labels[ids]
        pad = end - n if end > n else 0
        self._cursor = end
        return DataBatch(data=[csr], label=[array(label)], pad=pad)


class MNISTIter(DataIter):
    """IDX-format MNIST reader (reference: ``src/io/iter_mnist.cc``).

    ``image``/``label`` point at the idx3/idx1 files (optionally .gz)."""

    def __init__(self, image, label, batch_size=1, shuffle=False, flat=False,
                 seed=0, silent=True, input_shape=None, **kwargs):
        super().__init__(batch_size)
        self._images = self._read_idx(image, expect_dims=3)
        self._labels = self._read_idx(label, expect_dims=1)
        if self._images.shape[0] != self._labels.shape[0]:
            raise MXNetError("MNIST image/label count mismatch")
        self.flat = flat
        self.shuffle = shuffle
        self._rng = _np.random.RandomState(seed)
        self._order = _np.arange(self._images.shape[0])
        self.reset()

    @staticmethod
    def _read_idx(path, expect_dims):
        import gzip
        import struct

        op = gzip.open if str(path).endswith(".gz") else open
        with op(path, "rb") as f:
            raw = f.read()
        zero, dtype_code, ndim = raw[0] | raw[1], raw[2], raw[3]
        if zero != 0 or dtype_code != 0x08:
            raise MXNetError(
                f"{path} is not a uint8 idx file (magic "
                f"{raw[:4].hex()}; expected 0000 08 xx)")
        if ndim != expect_dims:
            raise MXNetError(f"idx file {path}: expected {expect_dims} dims, "
                             f"got {ndim}")
        dims = struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
        data = _np.frombuffer(raw, dtype=_np.uint8, offset=4 + 4 * ndim)
        return data.reshape(dims)

    def reset(self):
        self._cursor = 0
        if self.shuffle:
            self._rng.shuffle(self._order)

    @property
    def provide_data(self):
        h, w = self._images.shape[1:]
        shape = (self.batch_size, h * w) if self.flat else \
            (self.batch_size, 1, h, w)
        return [DataDesc("data", shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def next(self):
        n = self._images.shape[0]
        if self._cursor + self.batch_size > n:
            raise StopIteration
        ids = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        imgs = self._images[ids].astype("float32") / 255.0
        if self.flat:
            imgs = imgs.reshape(self.batch_size, -1)
        else:
            imgs = imgs[:, None, :, :]
        return DataBatch(data=[array(imgs)],
                         label=[array(self._labels[ids].astype("float32"))],
                         pad=0)
