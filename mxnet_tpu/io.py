"""Legacy data iterators.

Reference: ``python/mxnet/io/io.py`` (DataIter, DataBatch, NDArrayIter,
ResizeIter, PrefetchingIter) over the C++ iterator registry in ``src/io/``
(SURVEY.md §3.4).  The C++ threaded parser→batcher→prefetcher pipeline is
replaced by the Gluon DataLoader's thread-pool prefetch; these classes keep
the Module-era API surface.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{'_' + str(i) if i else ''}": d
                for i, d in enumerate(data)} if len(data) > 1 else \
            ({default_name: data[0]} if data else {})
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self.idx)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self.idx)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "roll_over":
            return self.cursor < self.num_data
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        out = []
        for _, v in arrays:
            end = self.cursor + self.batch_size
            ids = self.idx[self.cursor:min(end, self.num_data)]
            batch = v.asnumpy()[ids]
            if len(ids) < self.batch_size:  # pad
                pad = self.batch_size - len(ids)
                batch = _np.concatenate([batch, batch[:pad]])
            out.append(array(batch))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to size batches per epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetch wrapper (reference: mx.io.PrefetchingIter over
    dmlc::ThreadedIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import threading
        import queue

        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here supports one base iter")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._queue = queue.Queue(maxsize=2)
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            while True:
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batch)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        while self._thread.is_alive():
            try:
                if self._queue.get(timeout=0.1) is None:
                    break
            except Exception:
                break
        self.iter.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise NotImplementedError
